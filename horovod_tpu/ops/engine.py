"""The collective engine: Horovod's background coordinator, TPU-style.

TPU-native re-design of the reference's L2 core runtime
(``horovod/common/operations.cc`` ``BackgroundThreadLoop``/``RunLoopOnce``,
``tensor_queue.cc``, ``fusion_buffer_cache.cc``, ``response_cache.cc``,
``controller.cc`` — SURVEY.md §2a N1/N2/N6/N7/N8 and §3.2).

What survives from the reference (per SURVEY.md §7's design stance):
the *control plane* — a background cycle thread draining a thread-safe
tensor queue, negotiating which tensors are globally ready, fusing them, and
dispatching one collective per fused batch — plus timeline tracing and stall
inspection.  What changes: the *data plane*.  There is no NCCL ring or
fusion-buffer memcpy machinery to manage; a fused batch becomes a single
**jitted XLA micro-program** (flatten → concat → collective → split) compiled
once per (op, dtype, shape-set, process-set) and cached.  XLA owns the ICI
scheduling; the cache plays the role of the reference's response cache on the
steady-state hot path (SURVEY.md §7 "hard parts" #1 and #5).

Tensor representation ("stacked global array" convention): an eager tensor of
logical per-rank shape S is a ``jax.Array`` of shape ``[world, *S]`` sharded
over the world mesh axis — shard r is rank r's contribution.  Single-process
SPMD holds all shards; multi-process mode assembles the global array from each
process's local shards.  Results come back in natural global form:
allreduce/broadcast → replicated ``[*S]``; allgather → replicated concat;
alltoall/reducescatter → stacked, sharded ``[world, ...]``.
"""

from __future__ import annotations

import dataclasses
import enum
import itertools
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from ..compat import shard_map

import heapq

from . import collectives as C
from .scheduler import (  # noqa: F401  (re-export: public engine surface)
    CKPT_LANE, FAST_LANE, FUSED_LANE, PREFETCH_LANE, CheckpointChunk,
    FusedProgramCache, InflightRing, PingPongBuffers, StallInspector,
    TensorQueue, partition_name, partition_plan, pop_checkpoint_items,
    pop_gradient_batches,
)
from ..common.exceptions import ControlPlaneError
from ..utils.logging import get_logger

log = get_logger()


class CollectiveType(enum.Enum):
    ALLREDUCE = "allreduce"
    ALLGATHER = "allgather"
    BROADCAST = "broadcast"
    ALLTOALL = "alltoall"
    REDUCESCATTER = "reducescatter"
    BARRIER = "barrier"


@dataclasses.dataclass
class TensorTableEntry:
    """One pending collective request (reference: TensorTableEntry, N6)."""
    handle: int
    name: str
    ctype: CollectiveType
    tensor: Any                      # stacked global array [world, *S] (or None for barrier)
    reduce_op: C.ReduceOp = C.ReduceOp.AVERAGE
    root_rank: int = 0
    process_set_id: int = 0
    prescale_factor: Optional[float] = None
    postscale_factor: Optional[float] = None
    group_id: int = -1               # grouped ops execute atomically together
    donate: bool = False             # engine owns the buffer: donate to XLA
    # Wire-dtype compression fused into the jitted program ("bf16"/"fp16"/
    # None): cast-down before the collective, cast-up after — halves ICI
    # bytes with zero extra launches (reference N18's cast kernels, done
    # the XLA way).  Reduction ops only; part of the fusion key AND the
    # negotiation digest (divergence would execute mismatched programs).
    compression: Optional[str] = None
    # ZeRO-sharded data plane (ISSUE 15): True for the reduce-scatter /
    # allgather legs of a sharded optimizer program; "full" (ISSUE 18)
    # for the legs of the full-parameter-sharded (FSDP) plane.  Part of
    # the fusion key AND the negotiation digest: a compiled sharded
    # program can never cross-serve an ordinary collective (or a
    # full-sharded one a state-only-sharded one) of the same shapes, and
    # a rank whose sharded= flag diverges from its peers fails
    # negotiation with attribution instead of executing a mismatched
    # program.
    sharded: Any = False               # False | True | "full"
    # Two-level data plane (ISSUE 17): per-call override of the engine's
    # HOROVOD_HIERARCHICAL_ALLREDUCE default — True forces the two-level
    # schedule for this entry, False forces flat, None defers to the
    # engine knob + HOROVOD_HIER_THRESHOLD crossover.  Part of the fusion
    # key but NOT the negotiation digest (results are bitwise-identical
    # either way for SUM/AVERAGE/MIN/MAX, so peers need not agree — but
    # the VALUE must still be rank-invariant, like sharded=, because
    # batching groups by fusion key; analyzer rule HVD110 checks that).
    hierarchical: Optional[bool] = None
    # Drain priority (higher drains first; default 0 = FIFO).  Stamped by
    # the DistributedOptimizer bindings with reverse-registration order so
    # first-needed gradients lead each cycle (ByteScheduler-style priority
    # scheduling); must be identical across ranks for a given name.
    priority: int = 0
    enqueue_time: float = 0.0
    # Latency fast lane (ISSUE 8): marked at the ready verdict for
    # sub-threshold ungrouped allreduces — the entry dispatches as its own
    # single-tensor batch through a persistent pre-compiled program,
    # skipping the fusion-buffer concat/split and the per-cycle program-
    # cache key construction entirely (bitwise-identical results).
    fast_lane: bool = False
    # FSDP parameter-prefetch lane (ISSUE 18): marked by the full-sharded
    # optimizer binding on the allgathers that rematerialize the next
    # bucket's parameters.  Routes the batch onto the PREFETCH backlog
    # lane (after FAST, before FUSED, budget-exempt) so bucket k+1's
    # gather overlaps bucket k's compute without perturbing gradient
    # dispatch order.  Part of the fusion key but NOT the digest, like
    # hierarchical= — peers need not agree, but the value must be
    # rank-invariant (HVD110) because batching groups by fusion key.
    prefetch: bool = False
    # Response-cache slot (stamped by the controller when this entry's
    # announce rides the warm-path bitvector; -1 until learned).  The
    # engine's persistent-program pin key: slot ids are server-assigned
    # and digest-scoped, so a compiled program pinned to a slot is valid
    # for exactly as long as the slot is (coordinated invalidation via
    # the controller's slot_drop_hook).
    cache_slot: int = -1
    # ByteScheduler-style partitioning: sub-tensors of a split parent
    # carry (parent_name, index, count) plus the parent entry; the parent
    # itself never enters the queue (synchronize reassembles from the
    # parts, invisibly to callers).
    partition: Optional[Tuple] = None
    parent: Any = None
    # Lifecycle trace span (horovod_tpu.trace): claimed at first drain when
    # tracing is armed, stamped at each phase boundary, committed at settle.
    # None whenever tracing is disarmed — every stamp site guards on it.
    span: Any = None
    # filled on completion:
    result: Any = None
    error: Optional[BaseException] = None
    done: threading.Event = dataclasses.field(default_factory=threading.Event)


def _fusion_key(e: TensorTableEntry) -> Tuple:
    """Entries with equal keys may fuse into one XLA program.

    dtype is deliberately NOT part of the key: a fused program groups leaves
    by dtype internally (one concat+psum per dtype) and XLA's collective
    combiner merges those into one wire transfer — this keeps grouped ops
    with mixed fp32/bf16 members atomic in a single batch (reference: group
    table N13 semantics).

    The partition COUNT (never the raw threshold bytes, mirroring the
    chunk-plan keying) distinguishes a partitioned sub-tensor's program
    from a same-shaped ordinary tensor's, so a slot-pinned part program
    can never cross-serve an unpartitioned entry; parts of equal-shaped
    parents still share one compiled program.
    """
    return (e.ctype, e.reduce_op, e.root_rank, e.process_set_id,
            e.prescale_factor, e.postscale_factor, e.compression,
            e.sharded, e.hierarchical, e.prefetch,
            e.partition[2] if e.partition is not None else 0)


# Sentinel for a tensor whose trace-span claim was dropped (ring full):
# marks the entry permanently untraceable for this collective, so later
# drains cannot re-claim it with a fresh drain time (which would fold the
# negotiation cycles already spent into the queue phase) and the recorder's
# dropped counter counts each entry once.  Every stamp/commit site treats
# it as "no span".
_SPAN_DROPPED = object()


def _live_span(e):
    """The entry's traceable span, or None (untraced / claim dropped)."""
    sp = e.span
    return None if (sp is None or sp is _SPAN_DROPPED) else sp


def _np_dtype(name: str) -> np.dtype:
    """numpy dtype from its string form, including ml_dtypes extensions
    (bfloat16/fp8) that ``np.dtype`` alone does not know."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


class CollectiveEngine:
    """Background coordinator: queue → negotiate → fuse → execute.

    Single-controller negotiation is local (everything submitted is ready —
    the one process is every rank).  Multi-process mode plugs a TCP
    controller in at ``self.controller`` so all processes agree on the
    response list before executing identical programs; the execution path
    below is shared by both modes.
    """

    def __init__(self, state):
        self._state = state
        cfg = state.config
        self.queue = TensorQueue()
        self.cache = FusedProgramCache(cfg.cache_capacity)
        self.stall = StallInspector(cfg.stall_check_time_s,
                                    cfg.stall_shutdown_time_s,
                                    cfg.stall_check_disable)
        self.cycle_time_s = cfg.cycle_time_ms / 1000.0
        self.inline_kick = cfg.inline_kick
        self.fusion_threshold = cfg.fusion_threshold_bytes
        # Pipelined data plane (HOROVOD_PIPELINE_CHUNK / HOROVOD_MAX_
        # INFLIGHT).  chunk 0 = off: one chunk per fused batch, the legacy
        # single-collective program (a true off, because atomic clusters
        # can exceed the fusion threshold — see _chunk_plan); >0 splits
        # the fusion buffer so cast-down → reduce → cast-up stages overlap
        # across chunks inside the jitted program.  Both runtime-tunable
        # (autotune coordinates in multi-process mode).
        self.pipeline_chunk_bytes = cfg.pipeline_chunk_bytes
        self.max_inflight = cfg.max_inflight
        self._inflight: Optional[InflightRing] = None
        # Pipeline observability (bench.py emits chunks_per_cycle /
        # inflight_depth on every JSON line; the timeline gets a per-cycle
        # "pipeline" counter track).
        self.pipeline_chunks_total = 0
        self.pipeline_dispatches = 0
        self.last_cycle_chunks = 0
        # Small-message latency war (ISSUE 8, docs/performance.md
        # "Latency fast lane").  fast_lane_threshold: ungrouped allreduces
        # below it skip the fusion buffer — single-tensor batches through
        # persistent pre-compiled programs (_fast_programs: slot id — or
        # name in single-controller mode — -> pinned program record,
        # invalidated via the controller's slot_drop_hook).
        # partition_threshold: tensors above it split at enqueue into
        # priority-inheriting sub-tensors (ByteScheduler) so a small
        # high-priority gradient preempts a huge transfer between parts;
        # synchronize() reassembles transparently.  The dispatch backlog
        # (_backlog, ring mode only) is what makes preemption real: ready
        # batches queue by (lane, priority) and feed the in-flight window
        # only as it has room, so a later cycle's hotter batch overtakes
        # a huge tensor's remaining parts instead of queueing behind them.
        self.fast_lane_threshold = cfg.fast_lane_threshold_bytes
        self.partition_threshold = cfg.partition_threshold_bytes
        self._fast_programs: Dict[Any, tuple] = {}
        self._pingpong: Optional[PingPongBuffers] = None
        self._staging_tokens: Dict[int, list] = {}
        self._backlog: List[tuple] = []       # heap: (lane, -prio, seq, batch)
        self._backlog_seq = itertools.count()
        # Checkpoint-lane staging (ISSUE 14): submit_checkpoint_io runs
        # on the TRAINING thread while the cycle thread heappops the
        # backlog — heap mutation is not thread-safe, so cross-thread
        # submissions land here (own lock) and the cycle thread folds
        # them into the heap at its next turn.
        self._ckpt_staging: List = []
        self._ckpt_staging_lock = threading.Lock()
        self.fast_lane_dispatches = 0         # fast-lane batches dispatched
        self.fast_lane_hits = 0               # ... served by a pinned program
        self.partition_splits = 0             # parents split at enqueue
        # Resilient state plane (ISSUE 14, docs/fault_tolerance.md):
        # checkpoint shard writes ride the SAME backlog at CKPT_LANE —
        # strictly after every gradient batch, popped by their own
        # per-cycle budget so the durability stream overlaps training
        # without touching gradient dispatch order or the control plane
        # (checkpoint chunks are local I/O, never negotiated).
        self.ckpt_lane_budget = max(1, int(cfg.ckpt_lane_budget))
        self.ckpt_chunks_dispatched = 0
        self.stateplane = None
        if cfg.ckpt_dir:
            # One plane per directory per PROCESS (stateplane.obtain):
            # it survives elastic re-init like the per-host agent — a
            # survivor's in-memory epoch is exactly what a re-joining
            # rank restores from, so it must outlive the generation.
            from ..elastic.stateplane import obtain as _obtain_plane
            self.stateplane = _obtain_plane(
                cfg.ckpt_dir, rank=max(0, cfg.rank_env),
                world=max(1, cfg.size_env), engine=self,
                chunk_bytes=cfg.ckpt_chunk_bytes)
        self.hierarchical_allreduce = cfg.hierarchical_allreduce
        self.hierarchical_allgather = cfg.hierarchical_allgather
        self.hierarchical_broadcast = cfg.hierarchical_broadcast
        self._hier_local_size = cfg.hierarchical_local_size
        # Two-level data plane (ISSUE 17): payload crossover + explicit
        # slice membership override.  hier_threshold_bytes is a local
        # knob like pipeline_chunk_bytes — autotunable, never negotiated.
        self.hier_threshold_bytes = cfg.hier_threshold_bytes
        self.slice_map = cfg.slice_map
        # Per-process-set slice topology, derived once (device attrs /
        # HOROVOD_SLICE_MAP / local-size knob — parallel/topology.py) and
        # probed on every dispatch by the crossover decision.
        self._slice_topos: Dict[int, Any] = {}
        # Leg counters: proof the two-level path actually engaged.  One
        # hier dispatch = 2 intra-slice (ICI) legs (reduce-scatter +
        # allgather) + 1 cross-slice (DCN) leg.
        self.hier_dispatches = 0
        self.hier_intra_legs = 0
        self.hier_cross_legs = 0
        # Two-level allgather legs (ISSUE 18 satellite — the knob was a
        # no-op until now): one hier-AG dispatch = 1 intra-slice (ICI)
        # gather leg + 1 cross-slice (DCN) leader-exchange leg.
        self.hier_ag_dispatches = 0
        self.hier_ag_intra_legs = 0
        self.hier_ag_cross_legs = 0
        # Two-level broadcast legs (ISSUE 19 satellite — serving's weight
        # fan-out made this path hot): one hier-broadcast dispatch = 1
        # cross-slice (DCN) leader-exchange leg + 1 intra-slice (ICI)
        # fan-out leg.
        self.hier_bcast_dispatches = 0
        self.hier_bcast_intra_legs = 0
        self.hier_bcast_cross_legs = 0
        # Non-uniform HOROVOD_SLICE_MAP rejections (ISSUE 18 satellite):
        # counted once per process set (the topology probe is cached), so
        # mixed-size fleets can see WHY collectives stayed flat.
        self.slice_map_fallbacks = 0
        # FSDP parameter-prefetch lane (ISSUE 18): PREFETCH-lane batches
        # dispatched, and how many of those were dispatched while an
        # earlier bucket's gather was still in flight (overlap engaged —
        # the acceptance criterion's evidence).
        self.prefetch_dispatches = 0
        self.prefetch_overlapped = 0
        self._handle_counter = itertools.count(1)
        self._handles: Dict[int, TensorTableEntry] = {}
        self._handles_lock = threading.Lock()
        self._cycle_lock = threading.Lock()  # serializes cycles (bg + kick)
        self._shutdown = threading.Event()
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._cycle_index = 0
        self.controller = None       # multi-process TCP controller (optional)
        # Control-plane fault latch (HVD303): set by _abort_engine when a
        # ControlPlaneError (dead peer / round timeout) surfaces from
        # negotiation.  Once set, the engine is cleanly down — every
        # pending/in-flight waiter was settled with the error, and new
        # enqueues raise it immediately instead of queueing into a dead
        # world.  Elastic re-init builds a fresh engine, clearing it.
        self._fault: Optional[BaseException] = None
        # Clean world-membership change (protocol v6, NOT a fault): set
        # when the coordinator's leave notice names peers that departed
        # via clean LEAVE.  World-level (default-process-set) work fails
        # with it — the control plane's world shrank but the data-plane
        # world is still the old fixed size, so executing a shrunk-world
        # verdict would wedge the transport — while /health stays ok and
        # no HVD303 is raised; the elastic wrapper re-rendezvouses keeping
        # current parameters.  Elastic re-init clears it with the engine.
        self._world_changed: Optional[BaseException] = None
        # Control-plane observability: cumulative negotiation wall time and
        # round count (multi-process mode only — single-controller cycles
        # have no negotiation).  bench.py derives negotiation_us_per_cycle;
        # the timeline gets a per-cycle counter track.
        self.negotiation_us_total = 0.0
        self.negotiation_cycles = 0
        self.last_negotiation_us = 0.0
        # Zero-RTT warm path (protocol v7): cycles whose verdict came from
        # the coordinator's speculative prediction — negotiate() returned
        # without waiting for the response, so the negotiation phase
        # collapses toward zero.  The dispatch path below is deliberately
        # identical for predicted and lock-step verdicts (same entries,
        # same deterministic batching, same programs): a mispredict never
        # reaches this layer — the controller absorbs it by merging the
        # next announce into the still-pending server entry, so results
        # stay bitwise identical and nothing needs un-dispatching here.
        self.spec_cycles = 0
        # Whole-cycle wall-time accounting (drain + negotiate + fuse +
        # dispatch): the per-rank numbers the monitor subsystem aggregates
        # into slowest-rank / cycle-time-spread straggler attribution
        # (horovod_tpu.monitor).  `monitor` is a MonitorAgent installed by
        # init() when HOROVOD_MONITOR=1 — None costs one attribute check
        # per cycle.
        self.cycle_us_total = 0.0
        self.cycle_count = 0
        self.last_cycle_ts = 0.0
        self.monitor = None
        # Distributed collective tracing (HOROVOD_TRACE, horovod_tpu.trace):
        # per-tensor lifecycle spans (queue/negotiation/copy_in/reduce/
        # drain) stamped through the cycle below, ring-buffered, optionally
        # written to a per-rank trace file, and digested into the monitor
        # side-channel.  None when disarmed — every stamp site is then one
        # attribute check (the bench trace A/B pins this at zero cost).
        from ..trace import maybe_install as _trace_install
        self.tracer = _trace_install(
            cfg, rank=cfg.rank_env if cfg.rank_env >= 0 else 0)
        # XLA:CPU executes collectives via blocking rendezvous on a shared
        # Eigen pool; back-to-back ASYNC launches can starve a participant
        # thread and abort the process ("Expected N threads to join the
        # rendezvous", reproducible on 1-core hosts with 8 virtual devices,
        # with or without this engine).  On the hermetic CPU tier, wait for
        # each fused program before launching the next; TPU keeps the fully
        # async pipeline (its executor serializes per-core streams).
        self._serialize_launches = jax.default_backend() == "cpu"
        # Cached off the hot dispatch path (engine is built after the jax
        # world forms): >1 ⇒ eager ops need the negotiation controller.
        self._world_processes = jax.process_count()
        # Opt-in runtime collective sanitizer (HVD_TPU_SANITIZER=1):
        # records the per-rank submission ledger and stamps entries with
        # seq/call-site tags the controller folds into its negotiation
        # digest, so cross-rank order divergence fails fast with call-site
        # attribution (analysis/runtime_sanitizer.py).  May replace
        # self.stall with a tightened, ledger-reporting inspector.
        from ..analysis import runtime_sanitizer as _rts
        self.sanitizer = _rts.maybe_install(self)
        self.autotuner = None        # reference N9 parameter manager
        if cfg.autotune:
            from .autotune import ParameterManager
            self.autotuner = ParameterManager(
                self, warmup_samples=cfg.autotune_warmup_samples,
                steps_per_sample=cfg.autotune_steps_per_sample,
                log_path=cfg.autotune_log,
                max_evals=cfg.autotune_max_evals)

    # ------------------------------------------------------------- lifecycle
    def start(self):
        self._thread = threading.Thread(
            target=self._background_loop, name="hvd-tpu-coordinator", daemon=True)
        self._thread.start()

    def quiesce(self, timeout: float = 10.0) -> bool:
        """Stop the cycle thread at a round boundary for a CLEAN departure.

        Sets the shutdown flag and joins the thread WITHOUT severing the
        controller socket first: in a healthy world the in-flight
        lock-step round completes in milliseconds and the thread exits at
        the loop check, leaving the socket quiet — the precondition for
        ``controller.leave()`` (the LEAVE frame must not interleave with a
        round in flight).  Returns True when the thread exited cleanly
        with no fault latched; False (thread wedged — a peer is already
        gone or the coordinator is stuck) tells the caller to fall back to
        the legacy ``interrupt()`` sever."""
        self._shutdown.set()
        self._wake.set()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout)
            if t.is_alive():
                return False
            self._thread = None
        return self._fault is None

    def stop(self):
        self._shutdown.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        # The cycle thread is gone: this thread is now the heap's sole
        # mutator, so staged checkpoint items can fold in safely.
        if self._fault is None:
            self._drain_ckpt_staging()
        if self._backlog and self._fault is None:
            # Undispatched ready batches (the preemptive backlog only
            # defers dispatch while the window is full): dispatch them now,
            # before the ring drains — their waiters must not outlive the
            # engine unsignalled.  Checkpoint-lane items run too (the
            # shutdown finishes the durable write instead of abandoning
            # a healthy epoch).  The fault path already settled both.
            while self._backlog:
                lane, _, _, item = heapq.heappop(self._backlog)
                if lane == CKPT_LANE:
                    self._run_ckpt_item(item)
                else:
                    self._perform_operation(item)
        if self._inflight is not None:
            # Settles every dispatched batch first: a waiter blocked in
            # synchronize() must never outlive the watcher unsignalled.
            self._inflight.stop()
            self._inflight = None
        if self.tracer is not None:
            # After the ring: settling commits spans, and the trace file
            # must hold them all before the final flush.
            self.tracer.close()
        if self.stateplane is not None:
            # After the backlog drain above: any in-flight durable write
            # has finished (or failed with attribution).  DETACH, never
            # close — the plane (its shard server + in-memory epoch)
            # survives the engine exactly like the per-host agent, so a
            # re-joining rank can still restore from this survivor while
            # the world re-forms.  Commits between generations write
            # inline.
            if self.stateplane.engine is self:
                self.stateplane.engine = None
            self.stateplane = None

    def _abort_engine(self, exc: BaseException, busy: bool = False):
        """Clean engine shutdown on a control-plane fault (HVD303).

        Invariant restored here: NO waiter may hang.  Every entry still
        queued is settled with the error, the in-flight ring fails its
        window without blocking on device results that may never come
        (a collective whose participant died can block forever), new
        enqueues raise immediately, and the monitor's ``/health`` flips
        to ``peer_dead`` with the dead-rank list.  Runs on the cycle
        thread; idempotent.

        ``busy`` is the caller's hint that the failing cycle itself was
        carrying entries; together with the queue/ring state it picks the
        log severity — losing a peer with NO work outstanding is the
        shape of an ordinary staggered clean shutdown (the first rank to
        leave severs its socket and the server declares it dead; no wire
        protocol distinguishes that from a crash), so it must not put an
        ERROR in every clean run's logs."""
        if self._fault is not None:
            return
        self._fault = exc
        # Everything still waiting to negotiate fails now — the control
        # plane will never answer it.
        pending = self.queue.drain()
        idle = (not busy and not pending and not self._backlog
                and (self._inflight is None or len(self._inflight) == 0))
        if idle:
            log.warning(
                "control plane lost peer(s) with no work outstanding — a "
                "staggered clean shutdown looks exactly like this (a peer "
                "crash between bursts does too); shutting the engine down: "
                "%s", exc)
        else:
            log.error("control plane failed; shutting the engine down "
                      "cleanly: %s", exc)
        self._settle_queued(pending, exc)
        # Ready-but-undispatched batches parked in the preemptive backlog
        # are waiters too: settle them with the fault (their negotiation
        # lane is the one still open on the timeline).  Checkpoint-lane
        # items fail their write job instead — the epoch is abandoned
        # cleanly and the previous durable epoch remains the restore
        # point (never a torn write).  Staged-but-unfolded items get the
        # same treatment (runs on the cycle thread; later submits fail
        # fast on the latched fault).
        self._drain_ckpt_staging()
        while self._backlog:
            lane, _, _, item = heapq.heappop(self._backlog)
            if lane == CKPT_LANE:
                try:
                    item.fail(exc)
                except Exception:  # noqa: BLE001 - keep the abort going
                    log.exception("checkpoint-lane abort settle failed")
            else:
                self._settle_batch(item, None, exc)
        if self._pingpong is not None:
            # Both staging buffers settle exactly once: outstanding tokens
            # are released (idempotently — a racing watcher settle is a
            # no-op) and no dispatcher may block on a slot the wedged
            # watcher will never free.
            self._pingpong.abort()
        if self._inflight is not None:
            self._inflight.abort(exc)
        ctl = self.controller
        if ctl is not None:
            # Join waiters are part of the invariant too: the all-joined
            # verdict can never arrive from a dead control plane, and
            # hvd.join()'s default is timeout=None.
            try:
                ctl.fail_join(exc)
            except Exception:  # noqa: BLE001 - keep the abort going
                log.exception("failing join waiters failed")
        mon = self.monitor
        if mon is not None:
            try:
                mon.on_peer_failure(getattr(exc, "dead_ranks", []) or [],
                                    str(exc))
            except Exception:  # noqa: BLE001 - telemetry only
                log.exception("monitor peer-failure hook failed")
        # Stop cycling: further lock-step rounds against a stopped server
        # would only churn errors.  basics.shutdown() still runs the full
        # teardown (thread join, controller close) afterwards.
        self._shutdown.set()

    def _settle_queued(self, entries, exc: BaseException):
        """Settle queued-but-never-negotiated entries with a fault — THE
        one implementation of the no-waiter-may-hang invariant for the
        pre-negotiation stage (both _abort_engine's drain and the
        enqueue-vs-abort race path funnel through here, so the settle
        sequence cannot drift between them)."""
        tl = self._state.timeline
        tr = self.tracer
        for e in entries:
            e.error = exc
            if tl is not None:
                tl.end_activity(e.name, "QUEUE")
            sp = _live_span(e) if tr is not None else None
            if sp is not None:
                # Requeued entries may already carry a claimed span: commit
                # it as aborted so the ring slot is reclaimable.
                sp.error = True
                tr.commit(sp)
            self.queue.mark_done(e)
            e.done.set()

    @property
    def fault(self) -> Optional[BaseException]:
        """The control-plane fault (HVD303) that shut this engine down, or
        ``None`` while healthy.  Public contract: ``basics.shutdown`` keys
        its abrupt-teardown path off it, and fault-tolerance acceptance
        workers poll it to converge on the typed verdict.  Elastic re-init
        builds a fresh engine, which clears it."""
        return self._fault

    @property
    def world_changed(self) -> Optional[BaseException]:
        """The ``PeerLeftInterrupt`` latched when peers departed via clean
        LEAVE (protocol v6), or ``None``.  NOT a fault: ``fault`` stays
        ``None`` and ``/health`` stays ok — but world-level work fails
        with this until the elastic re-init forms the next generation
        (which builds a fresh engine, clearing it)."""
        return self._world_changed

    # ------------------------------------------------------------- submit API
    def enqueue(self, name: str, ctype: CollectiveType, tensor,
                reduce_op=C.ReduceOp.AVERAGE, root_rank: int = 0,
                process_set_id: int = 0, prescale_factor=None,
                postscale_factor=None, group_id: int = -1,
                donate: bool = False, compression: Optional[str] = None,
                priority: int = 0, sharded: bool = False,
                hierarchical: Optional[bool] = None) -> int:
        return self.enqueue_group([dict(
            name=name, ctype=ctype, tensor=tensor, reduce_op=reduce_op,
            root_rank=root_rank, process_set_id=process_set_id,
            prescale_factor=prescale_factor, postscale_factor=postscale_factor,
            group_id=group_id, donate=donate, compression=compression,
            priority=priority, sharded=sharded,
            hierarchical=hierarchical)])[0]

    def enqueue_group(self, items: Sequence[dict]) -> List[int]:
        """Enqueue several entries atomically w.r.t. the drain — a cycle
        sees all of them or none, so grouped members always negotiate (and
        batch) together (reference: group_table N13)."""
        if self._fault is not None:
            # The control plane is down (dead peer / round timeout): fail
            # fast with the original HVD303 error instead of queueing work
            # no negotiation round will ever answer.
            raise self._fault
        if self._world_changed is not None and any(
                int(kw.get("process_set_id", 0) or 0) == 0 for kw in items):
            # Peers departed via clean LEAVE (protocol v6): world-level
            # work cannot run until the world re-forms — fail fast with
            # the re-rendezvous interrupt, NOT an HVD303 fault.
            raise self._world_changed
        if self.controller is None and self._world_processes > 1:
            # A multi-process world without the launcher's negotiation
            # controller (pod auto-detect mode): eager collectives cannot
            # coordinate safely — the SPMD shard_map path is unaffected.
            raise RuntimeError(
                "eager collectives need the torovodrun-launched "
                "negotiation controller in a multi-process world; this "
                "process joined via pod auto-detect "
                "(HOROVOD_ONE_PROC_PER_HOST without HOROVOD_CONTROLLER_"
                "ADDR).  Launch with torovodrun, or use the in-graph "
                "psum/shard_map path")
        entries = []
        for kw in items:
            handle = next(self._handle_counter)
            entries.append(TensorTableEntry(handle=handle, **kw))
        # ByteScheduler partitioning: tensors above the threshold split
        # into priority-inheriting sub-tensors HERE, before the sanitizer
        # and the queue — the parts are what negotiate (under
        # deterministic sub-names every rank derives identically); the
        # parent stays handle-registered and is reassembled transparently
        # in synchronize().
        queued = self._maybe_partition(entries)
        if self.sanitizer is not None:
            # BEFORE the push: the cycle thread may drain a pushed entry
            # within microseconds, and an untagged digest racing a tagged
            # peer announce would be a false mismatch.
            self.sanitizer.observe(queued)
        with self._handles_lock:
            for e in entries:
                self._handles[e.handle] = e
        try:
            self.queue.push_many(queued)
        except ValueError:
            with self._handles_lock:
                for e in entries:
                    self._handles.pop(e.handle, None)
            if self.sanitizer is not None:
                # Duplicate-name rejection is rank-local: peers never see
                # these entries, so the advanced seq counters must be
                # rolled back or every later tag skews cross-rank.
                self.sanitizer.rollback(queued)
            raise
        tl = self._state.timeline
        if tl is not None:
            for e in queued:
                tl.start_activity(e.name, "QUEUE")
        fault = self._fault
        if fault is not None:
            # Lost the race with _abort_engine (the fault landed between
            # the guard above and the push).  Drain-as-claim: the queue pop
            # is atomic, so only entries still queued are ours to settle —
            # anything already drained (the abort's sweep, or a cycle that
            # then fails them) is settled exactly once by its drainer,
            # never twice (a double settle garbles the timeline's QUEUE
            # begin/end pairing).
            self._settle_queued(self.queue.drain(), fault)
        self._wake.set()
        return [e.handle for e in entries]

    def _maybe_partition(
            self, entries: List[TensorTableEntry]) -> List[TensorTableEntry]:
        """Split oversized reduction entries into sub-tensors (ByteScheduler
        partitioning): returns the queue-facing entry list — parents
        replaced by their parts.  Eligibility and the plan are pure
        functions of the negotiated (shape, dtype) plus the fleet-wide
        threshold, so every rank derives identical sub-names/shapes.
        ADASUM is excluded (its dot products span the whole vector —
        splitting changes the math); grouped members stay whole (groups
        are atomic)."""
        thr = self.partition_threshold
        if thr <= 0:
            return list(entries)
        out: List[TensorTableEntry] = []
        for e in entries:
            if (e.ctype != CollectiveType.ALLREDUCE or e.group_id >= 0
                    or e.tensor is None
                    or e.reduce_op == C.ReduceOp.ADASUM
                    or e.tensor.nbytes <= thr):
                out.append(e)
                continue
            shape = tuple(e.tensor.shape)
            per_rank = shape[1:]
            n = int(np.prod(per_rank)) if per_rank else 1
            # The threshold counts GLOBAL stacked bytes (the same
            # convention as the fusion threshold and the eligibility gate
            # above); the plan runs over the per-rank flat buffer, so
            # scale it down by world — parts come out ~threshold-sized
            # globally, and the gate and the plan can never disagree
            # about whether a split happens.
            per_rank_thr = max(1, thr // max(1, shape[0]))
            plan = partition_plan(n, e.tensor.dtype.itemsize, per_rank_thr)
            if len(plan) <= 1:
                out.append(e)
                continue
            arrays = self._split_parts(e, plan)
            k = len(plan)
            subs = []
            for i, arr in enumerate(arrays):
                sub = TensorTableEntry(
                    handle=next(self._handle_counter),
                    name=partition_name(e.name, i, k),
                    ctype=e.ctype, tensor=arr, reduce_op=e.reduce_op,
                    root_rank=e.root_rank,
                    process_set_id=e.process_set_id,
                    prescale_factor=e.prescale_factor,
                    postscale_factor=e.postscale_factor,
                    group_id=-1, donate=True, compression=e.compression,
                    priority=e.priority,          # priority inheritance
                    hierarchical=e.hierarchical)
                sub.partition = (e.name, i, k)
                sub.parent = e
                subs.append(sub)
            e.parts = subs
            e.partition_shape = per_rank
            e.tensor = None           # staged into the parts; free it
            out.extend(subs)
            self.partition_splits += 1
        return out

    def _split_parts(self, e: TensorTableEntry, plan) -> List[Any]:
        """One jitted splitter launch: flatten the per-rank payload and
        slice the plan's parts out, keeping the stacked [world, n_i]
        convention and the world-axis sharding (each part is an ordinary
        engine tensor from here on).  Cached like any other program."""
        shape = tuple(e.tensor.shape)
        mesh, axis, _world = self._mesh_axis(e.process_set_id)
        key = ("partition_split", shape, str(e.tensor.dtype), plan,
               e.process_set_id)

        def build():
            sharding = NamedSharding(mesh, P(axis))

            def split(x):
                flat = x.reshape(shape[0], -1)
                return tuple(flat[:, off:off + ln] for off, ln in plan)

            return jax.jit(split, out_shardings=sharding)

        fn = self.cache.get_or_build(key, build)
        return list(fn(e.tensor))

    def _assemble_parts(self, e: TensorTableEntry):
        """Reassemble a partitioned tensor's result from its settled parts
        (concat + reshape back to the per-rank logical shape) — runs on
        the synchronizing caller's thread, invisible to it."""
        parts = e.parts
        per_rank = tuple(e.partition_shape)
        key = ("partition_join",
               tuple(tuple(s.result.shape) for s in parts),
               str(parts[0].result.dtype), per_rank)

        def build():
            def join(*xs):
                flat = (jnp.concatenate([x.reshape(-1) for x in xs])
                        if len(xs) > 1 else xs[0].reshape(-1))
                return flat.reshape(per_rank)

            return jax.jit(join)

        fn = self.cache.get_or_build(key, build)
        return fn(*[s.result for s in parts])

    def synchronize(self, handle: int, timeout: Optional[float] = None):
        """Block until the handle's collective completed; return result.

        Reference parity: ``horovod/torch/mpi_ops.py synchronize()``.
        Partitioned entries wait on every part and reassemble — callers
        cannot tell a split tensor from a whole one.
        """
        with self._handles_lock:
            e = self._handles.get(handle)
        if e is None:
            raise ValueError(f"Unknown handle {handle}")
        parts = getattr(e, "parts", None)
        if parts is not None:
            deadline = (None if timeout is None
                        else time.monotonic() + timeout)
            for s in parts:
                left = (None if deadline is None
                        else max(0.0, deadline - time.monotonic()))
                if not s.done.wait(left):
                    raise TimeoutError(
                        f"Collective {e.name!r} did not complete within "
                        f"{timeout}s ({sum(1 for p in parts if p.done.is_set())}"
                        f"/{len(parts)} parts settled)")
            with self._handles_lock:
                self._handles.pop(handle, None)
            err = next((s.error for s in parts if s.error is not None), None)
            if err is not None:
                raise err
            if e.result is None:
                e.result = self._assemble_parts(e)
            return e.result
        if not e.done.wait(timeout):
            raise TimeoutError(f"Collective {e.name!r} did not complete "
                               f"within {timeout}s")
        with self._handles_lock:
            self._handles.pop(handle, None)
        if e.error is not None:
            raise e.error
        return e.result

    def poll(self, handle: int) -> bool:
        with self._handles_lock:
            e = self._handles.get(handle)
        if e is None:
            return True
        parts = getattr(e, "parts", None)
        if parts is not None:
            return all(s.done.is_set() for s in parts)
        return e.done.is_set()

    # ------------------------------------------------------- checkpoint lane
    def submit_checkpoint_io(self, items: Sequence) -> None:
        """Queue checkpoint-lane work items (ISSUE 14): shard-chunk
        writes from the state plane, scheduled at :data:`CKPT_LANE` —
        strictly after every gradient batch, popped by their own
        per-cycle budget (``HOROVOD_CKPT_LANE_BUDGET``).  Items are
        plain local-I/O callables, never negotiated: zero control-plane
        bytes, no cross-rank ordering requirement.  After a fault the
        lane is closed — items fail immediately so the write job
        abandons its epoch instead of queueing into a dead engine."""
        # Stage, never touch the heap: this runs on the TRAINING thread
        # (state.commit), and heappush racing the cycle thread's heappop
        # would corrupt the backlog ordering every rank must share.  The
        # cycle thread folds the staging in at its next turn.  The fault/
        # shutdown check lives INSIDE the staging lock: _abort_engine
        # latches the fault BEFORE draining the staging under this same
        # lock, so an item either lands before that drain (and is failed
        # there) or observes the latched fault here — never neither (an
        # unlocked check could stage into an already-aborted engine,
        # leaving the write job neither run nor failed and commit(wait)
        # blocked for its full timeout).
        with self._ckpt_staging_lock:
            fault = self._fault
            stopped = fault is not None or self._shutdown.is_set()
            if not stopped:
                self._ckpt_staging.extend(items)
        if stopped:
            for it in items:
                try:
                    it.fail(fault or RuntimeError("engine stopped"))
                except Exception:  # noqa: BLE001 - settle the rest
                    log.exception("checkpoint item fail hook failed")
            return
        self._wake.set()

    def _drain_ckpt_staging(self) -> None:
        """Fold staged checkpoint items into the backlog heap — CYCLE
        THREAD ONLY (the heap has exactly one mutator)."""
        with self._ckpt_staging_lock:
            items, self._ckpt_staging = self._ckpt_staging, []
        for it in items:
            heapq.heappush(
                self._backlog,
                (CKPT_LANE, -int(getattr(it, "priority", 0)),
                 next(self._backlog_seq), it))

    def _run_ckpt_item(self, item) -> None:
        """Dispatch one checkpoint-lane item on the cycle thread.  The
        item owns its own retries/failure attribution (the state plane's
        write job); the engine only guarantees a raising item cannot
        kill the cycle loop."""
        try:
            item.run()
            self.ckpt_chunks_dispatched += 1
        except BaseException:  # noqa: BLE001 - the cycle must survive
            log.exception("checkpoint-lane item %r failed",
                          getattr(item, "name", item))

    # ------------------------------------------------------------- main loop
    def _background_loop(self):
        while not self._shutdown.is_set():
            self._wake.wait(timeout=self.cycle_time_s)
            self._wake.clear()
            try:
                self.run_loop_once()
            except Exception:       # pragma: no cover - engine bug surface
                log.exception("coordinator cycle failed")

    def kick(self):
        """Hint that a caller is about to block on a just-enqueued handle.

        Single-controller mode: run the cycle INLINE on the calling thread —
        the submit→wake→cycle-thread→done→waiter round trip costs two thread
        handoffs that dominate small-tensor latency (VERDICT r3 weak #3);
        executing the drain/fuse/dispatch pipeline here removes both while
        preserving fusion (a concurrent burst drains into the same cycle).
        Multi-process mode: negotiation must stay on the lock-step cycle
        thread; just wake it.

        ``HOROVOD_INLINE_KICK=0`` disables the inline path (falling back to
        waking the cycle thread) — the A/B knob behind the recorded
        inline-vs-threaded dispatch-latency evidence
        (``tools/latency_evidence.py``).
        """
        if self.controller is None and self.inline_kick:
            self.run_loop_once()
        else:
            self._wake.set()

    def run_loop_once(self):
        """One coordinator cycle (reference: RunLoopOnce, SURVEY.md §3.2).

        Serialized by ``_cycle_lock`` — the background thread and blocking
        submitters (``kick``) may race to run a cycle.

        Any failure during planning (negotiation error, stall-shutdown
        abort, timeline I/O) must fail the drained entries — never drop
        them — or waiters in ``synchronize()`` would hang forever.
        """
        with self._cycle_lock:
            self._run_cycle_locked()

    def _run_cycle_locked(self):
        t_cycle0 = time.perf_counter()
        self._cycle_index += 1
        tl = self._state.timeline
        if tl is not None:
            tl.mark_cycle(self._cycle_index)
        self._drain_ckpt_staging()
        entries = self.queue.drain()
        if not entries and self.controller is None and not self._backlog:
            # (The backlog check keeps the checkpoint lane draining on
            # otherwise-idle single-controller cycles.)
            return
        tr = self.tracer
        t_trace0 = t_drain = 0.0
        if tr is not None:
            t_drain = time.monotonic()
            t_trace0 = t_drain - (time.perf_counter() - t_cycle0)
            for e in entries:
                if e.span is None:
                    # queue phase closes at this first drain; requeued
                    # entries keep their span (still in negotiation).  A
                    # dropped claim latches the sentinel: claim at most
                    # once per entry.
                    e.span = tr.begin(e.name, e.enqueue_time, t_drain) \
                        or _SPAN_DROPPED
        # Multi-process mode: every rank must complete a (possibly empty)
        # lock-step negotiation round each cycle, or peers with pending
        # tensors would block on this rank's missing frame.
        try:
            responses, not_ready = self._compute_response_list(entries)
        except BaseException as exc:  # noqa: BLE001 - propagate to waiters
            if isinstance(exc, ControlPlaneError):
                ctl = self.controller
                if ctl is not None and getattr(ctl, "interrupted", False):
                    # Expected teardown: basics.shutdown() severed the
                    # lock-step socket to unblock this thread, which makes
                    # the in-flight round fail exactly like a peer death.
                    # Not a fault — settle and exit quietly (stop() joins
                    # us next) instead of logging HVD303 and flipping
                    # /health to peer_dead on every clean multi-process
                    # shutdown.
                    pass
                else:
                    # A dead peer / missed round deadline: the control
                    # plane cannot recover in place — shut the engine down
                    # cleanly, settling EVERY outstanding waiter with the
                    # error (the elastic wrapper then restores +
                    # re-rendezvouses; static jobs fail fast with HVD303
                    # attribution instead of hanging).  MUST run before
                    # this cycle's waiters are released below: a waiter
                    # that wakes first reads engine.fault in
                    # basics.shutdown() to pick the abrupt teardown — a
                    # still-None fault would route a poisoned jax world
                    # through the graceful shutdown barrier it can never
                    # complete.
                    self._abort_engine(exc, busy=bool(entries))
            for e in entries:
                e.error = exc
                sp = _live_span(e) if tr is not None else None
                if sp is not None:
                    sp.error = True
                    tr.commit(sp)
                self.queue.mark_done(e)
                e.done.set()
            return
        if not_ready:
            self.queue.requeue(not_ready)
        t_ready = 0.0
        if tr is not None and responses:
            # Globally-ready verdict: negotiation phase closes.  The cycle
            # id is the cross-rank correlation key — the controller's
            # lock-step round counter is identical on every rank for the
            # same round; single-controller mode uses the local index.
            t_ready = time.monotonic()
            ctl = self.controller
            cyc_id = ctl.rounds if ctl is not None else self._cycle_index
            for batch in responses:
                for e in batch:
                    sp = _live_span(e)
                    if sp is None:
                        # ONLY synthesized join entries claim here (they
                        # never drained, so ready-time is their drain).
                        # An ordinary entry whose drain-time claim was
                        # dropped (ring full) stays untraced: re-claiming
                        # it now would fold its negotiation time into the
                        # queue phase and skew the attribution exactly
                        # under the load that saturates the ring.
                        if e.span is not None or \
                                not getattr(e, "trace_synthesized", False):
                            continue
                        sp = tr.begin(e.name, e.enqueue_time, t_ready)
                        e.span = sp or _SPAN_DROPPED
                    if sp is not None:
                        sp.t_ready = t_ready
                        sp.cycle = cyc_id
                        if ctl is not None and sp.slot < 0:
                            sp.slot = ctl.slot_of(e)
        cycle_chunks = 0
        ring = self._inflight_ring()
        if ring is None:
            for batch in responses:
                cycle_chunks += self._perform_operation(batch)
        else:
            # Preemptive dispatch backlog (ByteScheduler): ready batches
            # queue by (lane, priority, arrival) and each cycle dispatches
            # every fast-lane batch plus up to `max_inflight` fused
            # batches — leftovers wait HERE, where a later cycle's
            # higher-priority batch (or any fast-lane batch) overtakes
            # them.  This is what partitioning buys: a huge tensor's
            # remaining parts yield mid-transfer to a small hot gradient.
            # The budget is deliberately a pure function of knob + heap
            # state (never of local ring occupancy): every rank pushes
            # identical batches with identical (lane, priority, arrival)
            # keys, so every rank pops — and therefore LAUNCHES — in the
            # identical order, which cross-process XLA collectives
            # require.  An over-eager pop just blocks briefly in the
            # ring's bounded submit, exactly like the pre-backlog path.
            # Checkpoint-lane items (ISSUE 14) sort after BOTH gradient
            # lanes and never touch the fused budget — pop_gradient_
            # batches is the identical budget rule with a CKPT_LANE
            # guard, so gradient dispatch order is bitwise-unchanged
            # with checkpointing armed (pinned by the dispatch-order
            # tests).
            for batch in responses:
                if batch[0].fast_lane:
                    lane = FAST_LANE
                elif batch[0].prefetch:
                    # FSDP parameter gathers (ISSUE 18): after FAST,
                    # before FUSED, budget-exempt — bucket k+1's gather
                    # launches ahead of the gradient stream without
                    # consuming its in-flight budget or reordering it.
                    lane = PREFETCH_LANE
                    self.prefetch_dispatches += 1
                    for e in batch:
                        sp = _live_span(e)
                        if sp is not None:
                            sp.prefetch = True
                else:
                    lane = FUSED_LANE
                prio = max(e.priority for e in batch)
                heapq.heappush(self._backlog,
                               (lane, -prio, next(self._backlog_seq), batch))
            for batch in pop_gradient_batches(
                    self._backlog, max(1, int(self.max_inflight))):
                cycle_chunks += self._perform_operation(batch)
        # Checkpoint-lane tail (both dispatch modes): once no gradient
        # batch remains poppable this cycle, a bounded number of shard-
        # chunk writes ride the cycle's tail — the overlap-scheduled
        # durability stream.
        for item in pop_checkpoint_items(self._backlog,
                                         self.ckpt_lane_budget):
            self._run_ckpt_item(item)
        if self._backlog:
            # Leftovers (either lane) must not wait out a long cycle
            # timer: run the next cycle (and its negotiation round)
            # immediately.
            self._wake.set()
        if responses:
            self.last_cycle_chunks = cycle_chunks
            if tl is not None and tl.enabled:
                tl.counter("pipeline", {
                    "chunks": cycle_chunks,
                    "inflight": len(self._inflight)
                    if self._inflight is not None else 0})
        if tr is not None and responses:
            ctl = self.controller
            tr.cycle(ctl.rounds if ctl is not None else self._cycle_index,
                     t_trace0, t_drain, t_ready, time.monotonic(),
                     sum(len(b) for b in responses),
                     self.last_negotiation_us if ctl is not None else 0.0)
        if self.autotuner is not None and self.autotuner.tuning:
            nbytes = sum(e.tensor.nbytes for b in responses for e in b
                         if e.tensor is not None)
            self.autotuner.on_cycle(nbytes)
        dt_us = (time.perf_counter() - t_cycle0) * 1e6
        self.cycle_us_total += dt_us
        self.cycle_count += 1
        self.last_cycle_ts = time.time()
        if self.monitor is not None:
            self.monitor.on_cycle(dt_us)

    # --------------------------------------------------------- negotiation
    def _compute_response_list(self, entries) -> List[List[TensorTableEntry]]:
        """Group ready entries into fused batches (reference: N2
        ``ComputeResponseList``).

        Local mode: all entries are ready.  Grouped entries (group_id >= 0)
        must land in one batch (reference: group_table N13).  Batches are
        split at the fusion threshold, never across fusion keys.

        Returns ``(batches, not_ready)``; not-ready entries (multi-process
        negotiation) are re-queued by the caller for the next cycle.
        """
        not_ready: List[TensorTableEntry] = []
        if self.controller is not None:
            self.controller.synthesizer = self._synthesize_join_entry
            self.controller.slot_drop_hook = self._on_slot_drop
            # Zero-RTT dispatch-safety gate (protocol v7): a speculative
            # verdict is dispatched before peers have its real verdict,
            # so this thread must stay free to keep serving them rounds —
            # only the async in-flight window qualifies.  The serialized-
            # launch CPU tier (and an inline-settling window) block the
            # cycle thread inside the collective: a speculating rank
            # would starve the peer of the very frame it needs to launch,
            # deadlocking the fleet.  Pipelined rounds are unaffected
            # (a deferred verdict is already in every rank's buffer).
            self.controller.spec_dispatch_ok = (
                not self._serialize_launches and self.max_inflight > 1)
            t0 = time.perf_counter()
            ready, errored = self.controller.negotiate(entries)
            dt_us = (time.perf_counter() - t0) * 1e6
            self.negotiation_us_total += dt_us
            self.negotiation_cycles += 1
            self.last_negotiation_us = dt_us
            if getattr(self.controller, "last_round_speculative", False):
                self.spec_cycles += 1
            tl0 = self._state.timeline
            if tl0 is not None and tl0.enabled:
                st = self.controller.cache_stats
                ctl0 = self.controller
                tl0.counter("negotiation", {
                    "us": round(dt_us, 1), "cache_hits": st.hits,
                    "cache_misses": st.misses,
                    "cache_invalidations": st.invalidations,
                    # Zero-RTT speculation/pipelining (protocol v7).
                    "spec_hits": getattr(ctl0, "spec_hits", 0),
                    "spec_mispredicts": getattr(ctl0, "spec_mispredicts",
                                                0),
                    "inflight_rounds": getattr(ctl0, "inflight_rounds",
                                               0)})
            # Per-tensor negotiation failures (shape/dtype divergence across
            # ranks): fail ONLY those waiters; the runtime stays up
            # (reference: per-tensor error Responses, SURVEY.md N2).
            from ..common.controller import NegotiationError
            # Grouped ops are atomic (reference N13): one member failing
            # negotiation fails every local member of its group.  Name
            # sequences are aligned across ranks (see enqueue naming), so
            # every rank fails the same group deterministically.
            bad_groups = {e.group_id for e, _ in errored if e.group_id >= 0}
            if bad_groups:
                by_handle = {e.handle for e, _ in errored}
                for e in entries:
                    if e.group_id in bad_groups and e.handle not in by_handle:
                        errored.append((e, f"grouped collective aborted: a "
                                        f"member of group {e.group_id} failed "
                                        f"negotiation"))
                        # The member may still be mid-negotiation: clear the
                        # controller's announce bookkeeping so a retried op
                        # reusing the name renegotiates from scratch.
                        self.controller.forget(e)
            tl = self._state.timeline
            tr0 = self.tracer
            for e, msg in errored:
                e.error = NegotiationError(msg)
                if tl is not None:
                    tl.end_activity(e.name, "QUEUE")
                sp = _live_span(e) if tr0 is not None else None
                if sp is not None:
                    sp.error = True
                    tr0.commit(sp)
                self.queue.mark_done(e)
                # A failed entry is finished: clear the stall inspector's
                # live-stall state (and warn latch) like any completion.
                self.stall.progressed(e.name)
                e.done.set()
            errored_handles = {e.handle for e, _ in errored}
            done_handles = {e.handle for e in ready} | errored_handles
            not_ready = [e for e in entries if e.handle not in done_handles]
            entries = [e for e in ready if e.handle not in errored_handles]
            left = getattr(self.controller, "left_ranks", None)
            if left:
                # Clean world shrink (protocol v6 leave notice): world-level
                # verdicts were computed over the SHRUNK control-plane
                # world, but the data-plane world is still the old fixed
                # size — executing them would wedge the transport.  Fail
                # every default-process-set entry (ready AND still-pending)
                # with PeerLeftInterrupt: not a fault, /health stays ok,
                # and the elastic wrapper re-rendezvouses keeping current
                # parameters.  Sub-process-set collectives that exclude
                # the leavers keep flowing.
                if self._world_changed is None:
                    from ..common.exceptions import PeerLeftInterrupt
                    self._world_changed = PeerLeftInterrupt(left)
                exc_left = self._world_changed
                keep_r: List[TensorTableEntry] = []
                keep_nr: List[TensorTableEntry] = []
                poisoned: List[TensorTableEntry] = []
                for src, kept in ((entries, keep_r), (not_ready, keep_nr)):
                    for e in src:
                        if getattr(e, "process_set_id", 0) == 0:
                            self.controller.forget(e)
                            poisoned.append(e)
                        else:
                            kept.append(e)
                self._settle_queued(poisoned, exc_left)
                for e in poisoned:
                    self.stall.progressed(e.name)
                entries, not_ready = keep_r, keep_nr
                # Zero-RTT race closure (protocol v7): a SPECULATIVE
                # dispatch may have preceded this notice by one round — a
                # world collective launched from a predicted verdict in
                # the very round the leaver departed was never dispatched
                # by the leaver and can never complete (lock-step's
                # poison-before-dispatch guarantee does not cover it,
                # because the verdict was consumed before the notice was
                # readable).  With speculation armed, settle the
                # in-flight window with the same re-rendezvous interrupt
                # instead of letting its waiters wedge on a dead
                # collective: the elastic wrapper restores and re-runs
                # the step, exactly like any other world change.
                ctl2 = self.controller
                if (self._inflight is not None and len(self._inflight)
                        and getattr(ctl2, "spec_ready_after", 0) > 0
                        and getattr(ctl2, "spec_dispatch_ok", False)):
                    self._inflight.abort(exc_left)
        for e in entries:
            if self._state.timeline is not None:
                self._state.timeline.end_activity(e.name, "QUEUE")
                self._state.timeline.start_activity(
                    e.name, f"NEGOTIATE_{e.ctype.name}")
        self.stall.check(entries + not_ready)

        # Batching must be a pure function of the NEGOTIATED entry order —
        # never of local handle/group counters, which differ across ranks
        # (every rank must build byte-identical fused programs).  Grouped
        # members are pulled together at the first member's position.
        #
        # Latency fast lane: sub-threshold ungrouped allreduces skip the
        # fusion buffer entirely — each becomes its own single-tensor
        # batch, dispatched FIRST (they are the latency-critical blocking
        # ops; the threshold is identical on every rank, and nbytes
        # derives from the negotiated shape/dtype, so the fork is
        # deterministic fleet-wide).  Partitioned sub-tensors likewise
        # stay single-entry batches: the part — not the re-fused whole —
        # is the preemption unit.
        fast: List[TensorTableEntry] = []
        thr = self.fast_lane_threshold
        if thr > 0:
            rest: List[TensorTableEntry] = []
            for e in entries:
                if (e.group_id < 0 and e.partition is None
                        and e.ctype == CollectiveType.ALLREDUCE
                        and e.tensor is not None and e.tensor.nbytes < thr):
                    e.fast_lane = True
                    fast.append(e)
                else:
                    rest.append(e)
            entries = rest
        batches: List[List[TensorTableEntry]] = [[e] for e in fast]

        clusters: List[List[TensorTableEntry]] = []
        seen_groups: set = set()
        for e in entries:
            if e.group_id >= 0:
                if e.group_id in seen_groups:
                    continue
                seen_groups.add(e.group_id)
                clusters.append([m for m in entries
                                 if m.group_id == e.group_id])
            else:
                clusters.append([e])

        by_key: Dict[Tuple, List[List[TensorTableEntry]]] = {}
        for members in clusters:
            if members[0].partition is not None:
                batches.append(members)       # one batch per part, never
                continue                      # re-fused past the split
            by_key.setdefault(_fusion_key(members[0]), []).append(members)
        for key, key_clusters in by_key.items():
            cur: List[TensorTableEntry] = []
            cur_bytes = 0
            for members in key_clusters:
                mbytes = sum(m.tensor.nbytes for m in members
                             if m.tensor is not None)
                if cur and cur_bytes + mbytes > self.fusion_threshold:
                    batches.append(cur)
                    cur, cur_bytes = [], 0
                cur.extend(members)
                cur_bytes += mbytes
            if cur:
                batches.append(cur)
        return batches, not_ready

    # ----------------------------------------------------------- execution
    def _perform_operation(self, batch: List[TensorTableEntry]) -> int:
        """Dispatch one fused batch; returns its chunk count.

        With the in-flight window active (multi-process, MAX_INFLIGHT > 1)
        the entries are NOT settled here: the async launch enters the
        bounded ring and the completion watcher settles ``e.done`` off this
        thread, so the cycle thread proceeds straight to negotiating the
        next round while the device executes this one."""
        tl = self._state.timeline
        for e in batch:
            if tl is not None:
                tl.end_activity(e.name, f"NEGOTIATE_{e.ctype.name}")
                tl.start_activity(e.name, f"XLA_{e.ctype.name}")
        pp = self._pingpong
        if pp is not None and not batch[0].fast_lane:
            # Double-buffered fusion staging: claim one of the two ping-
            # pong slots per dtype group before launching, released by the
            # InflightRing watcher at settle — cycle N+1's copy_in may
            # overlap cycle N's reduce, N+2's may not.  Fast-lane batches
            # skip it: they stage no fusion buffer.
            keys = sorted({str(e.tensor.dtype) for e in batch
                           if e.tensor is not None})
            if keys:
                self._staging_tokens[id(batch)] = [pp.acquire(k)
                                                   for k in keys]
        try:
            results, chunks = self._execute_batch(batch)
        except BaseException as exc:  # noqa: BLE001 - propagate to waiters
            self._settle_batch(batch, None, exc)
            return 0
        tr = self.tracer
        if tr is not None:
            # copy_in phase closes: the fused program (fetch/build + the
            # async XLA launch — the fusion copy-in lives inside it) has
            # been dispatched; reduce runs from here to settle.  Fast-lane
            # entries served by a pinned program were already stamped
            # pre-invoke (their copy_in is the O(1) pin fetch — the
            # device wait belongs to the reduce phase); never restamp.
            t_launch = time.monotonic()
            for e in batch:
                sp = _live_span(e)
                if sp is not None and not sp.t_launch:
                    sp.t_launch = t_launch
        self.pipeline_chunks_total += chunks
        self.pipeline_dispatches += 1
        if batch[0].fast_lane:
            self.fast_lane_dispatches += 1
        ring = self._inflight_ring()
        if ring is None:
            self._settle_batch(batch, results)
        else:
            if tl is not None:
                for e in batch:
                    tl.start_activity(e.name, "INFLIGHT")
            ring.submit(batch, results)
        return chunks

    def _settle_batch(self, batch: List[TensorTableEntry], results,
                      error: Optional[BaseException] = None,
                      inflight: bool = False):
        """Completion epilogue (cycle thread inline, or the in-flight
        watcher): assign results/error, close timeline lanes, release
        waiters.  Must never raise — a lost settle hangs synchronize()."""
        tl = self._state.timeline
        tr = self.tracer
        t_result = time.monotonic() if tr is not None else 0.0
        tokens = self._staging_tokens.pop(id(batch), None)
        if tokens is not None and self._pingpong is not None:
            # Hand the ping-pong staging slots back FIRST: the cycle
            # thread may be blocked in acquire() waiting on exactly this
            # settle.  Idempotent per token — an abort that already
            # settled them is a no-op.
            for tok in tokens:
                self._pingpong.release(tok)
        if error is None:
            for e, r in zip(batch, results):
                e.result = r
        else:
            for e in batch:
                e.error = error
        for e in batch:
            try:
                if tl is not None:
                    if inflight:
                        tl.end_activity(e.name, "INFLIGHT")
                    tl.end_activity(e.name, f"XLA_{e.ctype.name}")
                sp = _live_span(e) if tr is not None else None
                if sp is not None:
                    sp.t_result = t_result
                    sp.t_done = time.monotonic()
                    sp.error = error is not None
                    tr.commit(sp)
                self.queue.mark_done(e)
                self.stall.progressed(e.name)
            except Exception:  # noqa: BLE001 - keep settling the rest
                # Timeline I/O (disk full, closed file) must never cost a
                # waiter its done signal — a lost set() is a hang, and on
                # the watcher thread it would take the whole window down.
                log.exception("settle bookkeeping failed for %r", e.name)
            finally:
                e.done.set()

    def _inflight_ring(self) -> Optional[InflightRing]:
        """The bounded dispatch window, or None for inline settling.

        Only the multi-process engine pipelines: single-controller cycles
        have no negotiation to overlap, and the inline-kick latency path
        relies on same-thread settling.  (The controller attaches after
        construction, hence the lazy build.)  CPU keeps launches serialized
        via ``_serialize_launches`` — the ring then only moves *settling*
        off the cycle thread, which still exercises the full machinery in
        the hermetic tier without the rendezvous-starvation hazard."""
        if self.max_inflight <= 1 or self.controller is None:
            return None
        if self._inflight is None:
            self._inflight = InflightRing(
                jax.block_until_ready,
                lambda b, r, err: self._settle_batch(b, r, err,
                                                     inflight=True),
                depth=self.max_inflight)
            # Double-buffered fusion staging rides the same lifecycle: the
            # ring's watcher is what hands the ping-pong slots back.
            self._pingpong = PingPongBuffers(slots=2)
        else:
            self._inflight.depth = max(1, int(self.max_inflight))
        return self._inflight

    def _mesh_axis(self, ps_id: int):
        ps = self._state.process_set_table.get(ps_id)
        return ps.mesh, ps.axis_name, ps.size()

    @staticmethod
    def _join_fill_value(ctype: CollectiveType, op: C.ReduceOp, dt: np.dtype):
        """A joined rank's implicit contribution: the reduction's IDENTITY
        element, so it cannot perturb the peers' result (reference: hvd.join
        'a tensor of zeros' — generalized to non-additive ops; plain zeros
        would zero out a PRODUCT or clamp a MAX of negatives)."""
        if ctype not in (CollectiveType.ALLREDUCE,
                         CollectiveType.REDUCESCATTER):
            return 0          # broadcast/allgather/alltoall payload: zeros
        if op == C.ReduceOp.PRODUCT:
            return 1
        if op in (C.ReduceOp.MIN, C.ReduceOp.MAX):
            hi = op == C.ReduceOp.MIN    # identity for MIN is the dtype max
            if dt == np.bool_:
                return hi
            try:
                info = np.finfo(dt)
            except ValueError:
                # numpy's finfo rejects ml_dtypes (bf16/fp8: "not inexact")
                # and iinfo rejects them too ("invalid integer data type V")
                # — ml_dtypes ships its own finfo for exactly this.
                try:
                    import ml_dtypes
                    info = ml_dtypes.finfo(dt)
                except ValueError:
                    info = np.iinfo(dt)
            return info.max if hi else info.min
        return 0              # SUM / AVERAGE (divisor stays world) / ADASUM

    def _synthesize_join_entry(self, name: str, digest: str,
                               group_id: int = -1) -> TensorTableEntry:
        """Implicit-contribution entry for a peer's collective while this
        rank is JOINED (reference: hvd.join).  The digest (the same one
        negotiation checks for consistency) carries op/dtype/shape/root,
        and the server-echoed group id preserves grouped batching, so this
        rank builds and executes the byte-identical fused program with a
        local identity contribution.
        """
        handle = next(self._handle_counter)
        now = time.monotonic()   # fresh age: must not trip the stall check
        if digest == "barrier":
            e = TensorTableEntry(handle=handle, name=name,
                                 ctype=CollectiveType.BARRIER, tensor=None,
                                 enqueue_time=now)
            # Tracer marker: synthesized entries never drain, so their
            # span is claimed at the ready verdict instead (and ONLY for
            # entries carrying this flag).
            e.trace_synthesized = True
            if self.sanitizer is not None:
                # The peer advanced its per-set seq by submitting; advance
                # ours too or every post-join collective mismatches on seq.
                self.sanitizer.observe_synthesized(e)
            return e
        parts = digest.split("|")
        ctype = CollectiveType(parts[0])
        dt = _np_dtype(parts[1])
        import ast
        shape = tuple(ast.literal_eval(parts[2]))
        op = C.ReduceOp[parts[3]]
        root = int(parts[4])
        pre = None if parts[5] == "None" else float(parts[5])
        post = None if parts[6] == "None" else float(parts[6])
        comp = None
        if len(parts) > 7 and parts[7] in ("bf16", "fp16"):
            # parts[7] is the wire-compression slot ("none" when off); the
            # server may append the sanitizer tag after it — trailing
            # parts stay ignored as before.
            comp = parts[7]
        # ZeRO-sharded digest dimension (appended ONLY for sharded ops, so
        # flat digests are byte-identical to the pre-sharding protocol):
        # the synthesized entry must carry the flag or its fusion key —
        # and therefore its fused program — would diverge from the peers'.
        # "sharded-full" (ISSUE 18) is the FSDP plane's token — a full-
        # sharded program must never cross-serve a state-only one.
        sharded: Any = False
        if len(parts) > 8:
            if parts[8] == "sharded":
                sharded = True
            elif parts[8] == "sharded-full":
                sharded = "full"
        ps = self._state.process_set_table.get(0)
        sharding = NamedSharding(ps.mesh, P(ps.axis_name))
        local_devs = [d for d in ps.mesh.devices.flat
                      if d.process_index == jax.process_index()]
        fill = np.full((1,) + shape,
                       self._join_fill_value(ctype, op, dt), dt)
        shards = [jax.device_put(fill, d) for d in local_devs]
        arr = jax.make_array_from_single_device_arrays(
            (ps.size(),) + shape, sharding, shards)
        e = TensorTableEntry(
            handle=handle, name=name, ctype=ctype, tensor=arr, reduce_op=op,
            root_rank=root, prescale_factor=pre, postscale_factor=post,
            group_id=group_id, donate=True, compression=comp,
            sharded=sharded, enqueue_time=now)
        e.trace_synthesized = True
        if self.sanitizer is not None:
            self.sanitizer.observe_synthesized(e)
        return e

    def _slice_topology(self, ps_id: int):
        """The slice-level structure of this process set's world
        (``parallel/topology.py``), derived once and cached, or None.

        Precedence: ``HOROVOD_SLICE_MAP`` (explicit override, CPU/
        simulated worlds) → device ``slice_index`` attributes (real
        multi-slice TPU) → ``HOROVOD_HIERARCHICAL_LOCAL_SIZE`` →
        per-process device counts (the PR-3 host-based derivation).
        Only the global process set is eligible — subgroup process sets
        keep the flat path.  A malformed slice map logs once and falls
        back flat instead of killing the cycle thread."""
        if ps_id != 0:
            return None
        if ps_id in self._slice_topos:
            return self._slice_topos[ps_id]
        from ..parallel import topology as slice_topo
        topo = self._state.topology
        ps = self._state.process_set_table.get(ps_id)
        devs = list(np.asarray(ps.mesh.devices).reshape(-1))
        try:
            st = slice_topo.slice_topology(
                devs, slice_map=self.slice_map,
                local_size=self._hier_local_size,
                local_counts=(topo.local_counts
                              if topo is not None else None))
        except ValueError as exc:
            # One-time attributed fallback (ISSUE 18 satellite): the topo
            # is cached per process set, so mixed-size fleets get exactly
            # one warning naming the offending slice sizes (the ValueError
            # text carries them) plus a monitor-scrapable counter — not a
            # silent flat path.
            self.slice_map_fallbacks += 1
            log.warning(
                "HOROVOD_SLICE_MAP rejected for process set %d (%s); "
                "hierarchical allreduce/allgather stay FLAT on this fleet "
                "— fix the slice map to uniform sizes to re-enable "
                "two-level collectives", ps_id, exc)
            st = None
        self._slice_topos[ps_id] = st
        return st

    def _hier_mesh(self, ps_id: int):
        """2-D (cross, local) mesh for two-level collectives, or None.

        Reference parity: ``HOROVOD_HIERARCHICAL_ALLREDUCE`` in
        ``horovod/common/ops/nccl_operations.cc`` (SURVEY.md N17) splits the
        world into NCCL-intra-node × MPI-cross-node; here the split is
        local = ICI within a slice, cross = DCN between slices, with the
        membership derived by ``_slice_topology``.  Ranks are slice-major
        (``common.topology.ordered_devices`` sorts slice_index first), so
        the reshape lays every slice along the ``local`` axis and the
        cross axis walks the leader ring in rank order — the DCN ring
        order derived from leader torus coordinates at rank assignment."""
        st = self._slice_topology(ps_id)
        if st is None:
            return None
        ps = self._state.process_set_table.get(ps_id)
        devs = np.asarray(ps.mesh.devices).reshape(st.num_slices,
                                                   st.local_size)
        return Mesh(devs, ("cross", "local"))

    def _hier_decision(self, e0: "TensorTableEntry", nbytes: int) -> bool:
        """Per-batch flat-vs-two-level verdict — a pure function of the
        negotiated batch (op/dtype/bytes), the engine knobs, and the
        fleet-static slice topology, so every rank decides identically
        with ZERO control-plane traffic (the knobs ride neither the
        digest nor the announce, same rule as HOROVOD_PIPELINE_CHUNK).

        ``nbytes`` counts per-rank payload bytes: the crossover trades
        the two extra phase latencies against the DCN byte savings,
        which scale with what each rank actually moves."""
        if e0.hierarchical is False:
            return False
        if e0.hierarchical is None and not self.hierarchical_allreduce:
            return False
        if e0.ctype != CollectiveType.ALLREDUCE:
            return False
        if e0.reduce_op not in (C.ReduceOp.SUM, C.ReduceOp.AVERAGE,
                                C.ReduceOp.MIN, C.ReduceOp.MAX,
                                C.ReduceOp.ADASUM):
            return False
        if e0.hierarchical is None and nbytes < self.hier_threshold_bytes:
            return False
        st = self._slice_topology(e0.process_set_id)
        if st is None:
            return False
        if e0.reduce_op == C.ReduceOp.ADASUM:
            # Two-level VHD needs power-of-two extents at both levels.
            from ..parallel.topology import hier_bit_orders
            if hier_bit_orders(st.local_size, st.num_slices) is None:
                return False
        return True

    def _hier_ag_decision(self, e0: "TensorTableEntry") -> bool:
        """Per-entry flat-vs-two-level verdict for allgather (ISSUE 18
        satellite — ``HOROVOD_HIERARCHICAL_ALLGATHER`` was a no-op knob
        until now).  Same override semantics as ``_hier_decision`` and the
        same zero-control-plane property: a pure function of the entry's
        ``hierarchical`` override, the engine knob, and the fleet-static
        slice topology.  No payload crossover — a two-level gather moves
        the same total bytes as flat (every rank still receives the full
        [world, *S] result); the win is that only the leader ring crosses
        DCN, so the decision is purely topological."""
        if e0.ctype != CollectiveType.ALLGATHER:
            return False
        if e0.hierarchical is False:
            return False
        if e0.hierarchical is None and not self.hierarchical_allgather:
            return False
        return self._slice_topology(e0.process_set_id) is not None

    def _hier_bcast_decision(self, e0: "TensorTableEntry") -> bool:
        """Per-entry flat-vs-two-level verdict for broadcast (ISSUE 19
        satellite — serving's versioned weight fan-out is the workload).
        Same override semantics and zero-control-plane property as
        ``_hier_ag_decision``: pure function of the entry's
        ``hierarchical`` override, the engine knob, and the fleet-static
        slice topology.  No payload crossover — two-level broadcast
        moves the same bytes to every rank; the win is that only the
        root→leader exchange crosses DCN (fan-out rides ICI), so the
        decision is purely topological."""
        if e0.ctype != CollectiveType.BROADCAST:
            return False
        if e0.hierarchical is False:
            return False
        if e0.hierarchical is None and not self.hierarchical_broadcast:
            return False
        return self._slice_topology(e0.process_set_id) is not None

    def _batch_payload_bytes(self, batch) -> int:
        """Per-rank payload bytes of a fused batch (stacked tensors carry
        [world, *S]; the per-rank shard is what rides the wire)."""
        total = 0
        for e in batch:
            t = e.tensor
            if t is None:
                continue
            world = max(1, int(t.shape[0])) if t.ndim else 1
            total += t.nbytes // world
        return total

    def _chunk_plan(self, ctype: CollectiveType, shapes, dtypes) -> Tuple:
        """Per-dtype-group chunk counts for a fused reduction.

        A pure function of (chunk knob, per-rank shapes, dtypes): every rank
        computes the same plan from the same negotiated batch, so the fused
        programs stay byte-identical.  The *counts* — not the raw chunk
        byte values — key the program cache: retuning the knob only
        recompiles when the plan actually changes, keeping program count
        bounded.  Empty plan = chunking off or a non-reduction op (gathers
        and permutes have no cast/reduce/cast stages to overlap).

        Knob 0 is a true OFF, not "fusion-threshold-sized chunks": an
        atomic cluster (one grouped_allreduce of the whole model, or a
        single oversized tensor) is never split by the batch planner, so
        it can exceed the threshold — deriving chunks from it would
        silently chunk default-config workloads."""
        if ctype != CollectiveType.ALLREDUCE or self.pipeline_chunk_bytes <= 0:
            return ()
        chunk = max(1, int(self.pipeline_chunk_bytes))
        groups: Dict[str, Tuple[int, int]] = {}   # dtype -> (elems, bytes)
        for s, dt in zip(shapes, dtypes):
            n = int(np.prod(s[1:])) if len(s) > 1 else 1
            b = n * _np_dtype(dt).itemsize
            e_, b_ = groups.get(dt, (0, 0))
            groups[dt] = (e_ + n, b_ + b)
        return tuple(min(max(1, -(-b // chunk)), max(1, e))
                     for e, b in groups.values())

    def _on_slot_drop(self, slot: int):
        """Controller invalidation hook: a response-cache slot this client
        dropped (eviction / forget / trim / id reuse) takes its pinned
        persistent program with it."""
        self._fast_programs.pop(slot, None)

    def _fast_pin_key(self, e: TensorTableEntry):
        """Persistent-program pin key: the server-assigned response-cache
        slot (digest-scoped, coordinated invalidation) when known, the
        tensor name in single-controller mode (no slots exist; the
        validity compare below keeps name reuse sound)."""
        return e.cache_slot if e.cache_slot >= 0 else e.name

    def _execute_fast_lane(self, e: TensorTableEntry, hier_now: bool):
        """Dispatch a fast-lane entry through its pinned pre-compiled
        program — zero fusion-key construction, zero chunk planning, zero
        program-cache tuple hashing on the warm path; one dict probe and
        a handful of scalar compares.  ``hier_now`` is the batch's
        flat-vs-two-level verdict (``_hier_decision``): the pin stores
        the verdict its program was built under, so a threshold retune
        that flips the schedule drops the pin and rebuilds — never
        serves a flat program to a two-level decision or vice versa.
        Returns ``(results, chunks)`` or None (no valid pin yet — the
        caller takes the regular path and pins the program it builds)."""
        rec = self._fast_programs.get(self._fast_pin_key(e))
        if rec is None:
            return None
        (fkey, shape, dtype, donate, chunk_knob, hier, fn, chunks) = rec
        if (shape != e.tensor.shape or dtype != e.tensor.dtype
                or donate != e.donate
                or chunk_knob != self.pipeline_chunk_bytes
                or hier != hier_now
                or fkey != _fusion_key(e)):
            # Stale pin (name reuse under new params, knob retune, ...):
            # drop it; the regular path rebuilds and re-pins.
            self._fast_programs.pop(self._fast_pin_key(e), None)
            return None
        self.fast_lane_hits += 1
        tr = self.tracer
        if tr is not None:
            sp = _live_span(e)
            if sp is not None and not sp.t_launch:
                # copy_in closes HERE, before the invoke: the fast lane
                # stages no fusion buffer and fetches no key — the device
                # wait that follows belongs to the reduce phase (this is
                # what makes copy_in ≈ 0 on the fast lane in the bench's
                # phase breakdown).
                sp.t_launch = time.monotonic()
        outs = fn(e.tensor)
        if not isinstance(outs, (list, tuple)):
            outs = [outs]
        if self._serialize_launches:
            jax.block_until_ready(outs)
        return list(outs), chunks

    def _execute_batch(self, batch: List[TensorTableEntry]):
        """Build-or-fetch the fused program and launch it; returns
        ``(results, chunk_count)`` — results may still be async (the
        in-flight watcher blocks on them) unless ``_serialize_launches``."""
        e0 = batch[0]
        if e0.ctype == CollectiveType.BARRIER:
            return [None for _ in batch], 0
        # Two-level crossover verdict — once per batch, BEFORE the fast
        # lane probe (the pin's validity record compares against it) and
        # before the cache key (the DECISION keys the program, never the
        # raw knobs: retuning HOROVOD_HIER_THRESHOLD only recompiles when
        # a batch actually changes schedule, mirroring chunk-plan keying).
        if e0.ctype == CollectiveType.ALLGATHER:
            # Two-level allgather verdict (ISSUE 18 satellite): per-entry,
            # same override semantics as allreduce (e.hierarchical True
            # forces, False forces flat, None defers to the knob), no
            # payload threshold — the FSDP prefetch gathers that make
            # this path hot are full-bucket-sized by construction.
            hier = self._hier_ag_decision(e0)
        elif e0.ctype == CollectiveType.BROADCAST:
            # Two-level broadcast verdict (ISSUE 19 satellite): per-entry,
            # purely topological like allgather — the serving weight
            # fan-out that makes this path hot is whole-model-sized.
            hier = self._hier_bcast_decision(e0)
        else:
            hier = self._hier_decision(e0, self._batch_payload_bytes(batch))
        if hier and e0.ctype == CollectiveType.ALLGATHER:
            self.hier_ag_dispatches += 1
            self.hier_ag_intra_legs += 1  # intra-slice gather (ICI)
            self.hier_ag_cross_legs += 1  # cross-slice leader exchange (DCN)
        elif hier and e0.ctype == CollectiveType.BROADCAST:
            self.hier_bcast_dispatches += 1
            self.hier_bcast_cross_legs += 1  # root → slice leaders (DCN)
            self.hier_bcast_intra_legs += 1  # leader → slice fan-out (ICI)
        elif hier:
            self.hier_dispatches += 1
            self.hier_intra_legs += 2     # reduce-scatter + allgather (ICI)
            self.hier_cross_legs += 1     # leader-ring allreduce (DCN)
            tr = self.tracer
            if tr is not None:
                st = self._slice_topology(e0.process_set_id)
                from ..parallel.topology import cross_fraction
                frac = cross_fraction(self._batch_payload_bytes(batch),
                                      st.world, st.local_size)
                for e in batch:
                    sp = _live_span(e)
                    if sp is not None:
                        sp.cross_frac = frac
        if e0.fast_lane and len(batch) == 1:
            fast = self._execute_fast_lane(e0, hier)
            if fast is not None:
                return fast
        mesh, axis, world = self._mesh_axis(e0.process_set_id)
        shapes = tuple(tuple(e.tensor.shape) for e in batch)
        dtypes = tuple(str(e.tensor.dtype) for e in batch)
        donate = tuple(e.donate for e in batch)
        plan = self._chunk_plan(e0.ctype, shapes, dtypes)
        key = (_fusion_key(e0), shapes, dtypes, donate, hier, plan)
        fn, hit = self.cache.get_or_build2(
            key, lambda: self._build_program(e0, shapes, dtypes, mesh, axis,
                                             world, donate, plan,
                                             hier=hier))
        if e0.fast_lane and len(batch) == 1:
            # Pin the program for the next submission of this tensor: the
            # record stores exactly the inputs the program was built from,
            # so the warm-path validity check is a few scalar compares.
            pin = self._fast_programs
            pin[self._fast_pin_key(e0)] = (
                key[0], e0.tensor.shape, e0.tensor.dtype, e0.donate,
                self.pipeline_chunk_bytes, hier,
                fn, sum(plan) if plan else 1)
            if e0.cache_slot >= 0:
                # Cold start pinned under the NAME (the slot was still
                # unlearned at that dispatch); now that the slot-keyed pin
                # exists, drop the orphan — it would never be probed again
                # but would hold a compiled-program reference and crowd
                # live pins out of the capacity bound.
                pin.pop(e0.name, None)
            while len(pin) > max(16, self.cache.capacity):
                pin.pop(next(iter(pin)))
        if hit:
            outs = fn(*[e.tensor for e in batch])
        else:
            # First invocation compiles; donation is best-effort and ops
            # whose output cannot alias the input (e.g. allgather) make XLA
            # warn at compile time.  Suppress only around this cold-path
            # compile — steady-state dispatch stays untouched and user
            # code keeps its own donation diagnostics.
            import warnings
            with warnings.catch_warnings():
                warnings.filterwarnings(
                    "ignore", message="Some donated buffers were not usable")
                outs = fn(*[e.tensor for e in batch])
        if not isinstance(outs, (list, tuple)):
            outs = [outs]
        if self._serialize_launches:
            jax.block_until_ready(outs)
        return list(outs), (sum(plan) if plan else 1)

    # Builders: one jitted micro-program per (fusion key, shape set).  The
    # fused allreduce flattens every tensor's per-rank shard, concatenates
    # into one [world, total] buffer (the fusion buffer, living purely as an
    # XLA temporary in HBM — reference N7 without the memcpy machinery),
    # runs ONE collective, and splits results out.
    def _build_program(self, proto: TensorTableEntry, shapes, dtypes, mesh,
                       axis, world, donate=(), plan=(), hier=None):
        ctype = proto.ctype
        # Engine-owned input buffers are donated to XLA so the fused
        # program may alias them in HBM instead of allocating fresh
        # outputs (reference N7's in-place fusion buffer, the XLA way;
        # SURVEY.md §7 hard-part #2).  XLA ignores unusable donations.
        dargs = tuple(i for i, d in enumerate(donate) if d)

        def _jit(fn):
            return jax.jit(fn, donate_argnums=dargs)

        if ctype == CollectiveType.ALLREDUCE:
            if hier is None:
                # Direct callers carry no dispatch-time crossover verdict:
                # the engine knob decides, threshold treated as met (the
                # pre-crossover contract for knob-armed builds).
                hier = self._hier_decision(proto, self.hier_threshold_bytes)
            if hier:
                # The crossover verdict already proved the slice topology
                # exists and the op is eligible (_hier_decision).
                hmesh = self._hier_mesh(proto.process_set_id)
                if hmesh is not None:
                    return self._build_hier_allreduce(
                        proto, shapes, dtypes, hmesh, world, _jit, plan)
            return self._build_allreduce(proto, shapes, dtypes, mesh, axis,
                                         world, _jit, plan)
        if ctype == CollectiveType.BROADCAST:
            if hier is None:
                # Direct callers carry no dispatch-time verdict.
                hier = self._hier_bcast_decision(proto)
            if hier:
                # The verdict already proved the slice topology exists.
                hmesh = self._hier_mesh(proto.process_set_id)
                if hmesh is not None:
                    return self._build_hier_broadcast(
                        proto, shapes, hmesh, world, _jit)
            return self._build_broadcast(proto, shapes, mesh, axis, world,
                                         _jit)
        if ctype == CollectiveType.ALLGATHER:
            if hier is None:
                # Direct callers carry no dispatch-time verdict.
                hier = self._hier_ag_decision(proto)
            if hier:
                # The verdict already proved the slice topology exists.
                hmesh = self._hier_mesh(proto.process_set_id)
                if hmesh is not None:
                    return self._build_hier_allgather(
                        proto, shapes, hmesh, world, _jit)
            return self._build_allgather(proto, shapes, mesh, axis, world,
                                         _jit)
        if ctype == CollectiveType.REDUCESCATTER:
            return self._build_reducescatter(proto, shapes, mesh, axis,
                                             world, _jit)
        if ctype == CollectiveType.ALLTOALL:
            return self._build_alltoall(proto, shapes, mesh, axis, world,
                                        _jit)
        raise ValueError(f"Unsupported collective: {ctype}")

    def _build_fused_reduce(self, proto, shapes, dtypes, mesh_, in_spec,
                            reduce_flat, _jit, plan=()):
        """Shared fused-reduction scaffold (flat + hierarchical allreduce):
        flatten each tensor's per-rank shard, concatenate per dtype (one
        reduce per distinct dtype — XLA's collective combiner merges them
        into a single wire transfer, keeping mixed-dtype groups atomic
        without promotion), apply pre/post scaling around ``reduce_flat``,
        and slice results back out.

        Wire compression (``proto.compression``): floating dtype groups are
        cast down to the wire dtype right before ``reduce_flat`` and cast
        back up right after, INSIDE the jitted program — XLA fuses both
        casts into the collective's producer/consumer, so the bytes over
        ICI halve with zero extra launches.  Prescale happens in the
        original dtype (before the down-cast) and postscale after the
        up-cast, keeping the lossy window as narrow as possible.

        Chunked pipelining (``plan``, one chunk count per dtype group in
        first-occurrence order): the fused flat buffer is split into even
        chunks and each chunk rides its own cast-down → reduce → cast-up
        stage, so XLA overlaps chunk i+1's casts with chunk i's collective
        (software-pipelined ICI).  Chunk boundaries never change which
        ranks reduce which element, so results are bitwise-identical to
        the single-chunk program."""
        pre, post = proto.prescale_factor, proto.postscale_factor
        wire = {"bf16": jnp.bfloat16, "fp16": jnp.float16}.get(
            proto.compression)
        per_rank_shapes = [s[1:] for s in shapes]
        sizes = [int(np.prod(s)) if s else 1 for s in per_rank_shapes]
        dtype_groups: Dict[str, List[int]] = {}
        for i, dt in enumerate(dtypes):
            dtype_groups.setdefault(dt, []).append(i)
        chunk_counts = list(plan) if plan else [1] * len(dtype_groups)

        def reduce_wire(flat):
            if (wire is not None and flat.dtype != wire
                    and jnp.issubdtype(flat.dtype, jnp.floating)):
                return reduce_flat(flat.astype(wire)).astype(flat.dtype)
            return reduce_flat(flat)

        def reduce_chunked(flat, nch):
            if nch <= 1 or flat.shape[0] <= 1:
                return reduce_wire(flat)
            per = -(-flat.shape[0] // nch)     # ceil; last chunk shorter
            return jnp.concatenate(
                [reduce_wire(flat[i * per:(i + 1) * per])
                 for i in range(nch)])

        def per_shard(*xs):
            # xs: per-rank values, each [*S] — flatten, fuse per dtype.
            outs: List[Any] = [None] * len(xs)
            for (dt, idxs), nch in zip(dtype_groups.items(), chunk_counts):
                flat = jnp.concatenate([xs[i].reshape(-1) for i in idxs]) \
                    if len(idxs) > 1 else xs[idxs[0]].reshape(-1)
                red = C._scale(reduce_chunked(C._scale(flat, pre), nch),
                               post)
                off = 0
                for i in idxs:
                    outs[i] = red[off:off + sizes[i]].reshape(per_rank_shapes[i])
                    off += sizes[i]
            return tuple(outs)

        def wrapper(*xs):
            # Each stacked input [world, *S] → shard [1, *S]; reshape inside.
            def body(*shards):
                return per_shard(*[s.reshape(s.shape[1:]) for s in shards])
            return shard_map(body, mesh=mesh_,
                             in_specs=tuple(in_spec for _ in shapes),
                             out_specs=tuple(P() for _ in shapes),
                             check_vma=False)(*xs)

        return _jit(wrapper)

    def _build_allreduce(self, proto, shapes, dtypes, mesh, axis, world,
                         _jit=jax.jit, plan=()):
        op = proto.reduce_op

        def reduce_flat(flat):
            if op in (C.ReduceOp.AVERAGE, C.ReduceOp.SUM):
                red = lax.psum(flat, axis)
                if op == C.ReduceOp.AVERAGE:
                    red = red / jnp.asarray(world, red.dtype) if jnp.issubdtype(
                        red.dtype, jnp.floating) else red // world
            elif op == C.ReduceOp.MIN:
                red = lax.pmin(flat, axis)
            elif op == C.ReduceOp.MAX:
                red = lax.pmax(flat, axis)
            elif op == C.ReduceOp.PRODUCT:
                g = lax.all_gather(flat, axis)
                red = jnp.prod(g, axis=0)
            elif op == C.ReduceOp.ADASUM:
                if world & (world - 1) == 0 and world > 1:
                    # Power-of-two world: true vector-halving-doubling over
                    # collective-permute — log2(n) rounds riding ICI
                    # neighbor links, ~2·|x| bytes per rank instead of the
                    # gather tree's n·|x| (reference adasum_mpi_operations
                    # VHDD; SURVEY.md §2c "re-derive halving-doubling on
                    # the torus axes").  Rounds walk physical torus axes
                    # innermost-first when coords exist.
                    from ..common.topology import torus_dims
                    from ..parallel.adasum import (adasum_allreduce_hd,
                                                   torus_bit_order)
                    try:
                        dims = torus_dims(list(mesh.devices.flat))
                    except Exception:  # pragma: no cover - cpu meshes
                        dims = None
                    red = adasum_allreduce_hd(
                        flat, axis, bit_order=torus_bit_order(world, dims))
                else:
                    # Non-power-of-two fallback: gather + pairwise tree.
                    from ..parallel.adasum import adasum_allreduce
                    red = adasum_allreduce(flat, axis)
            else:
                raise ValueError(f"Unknown ReduceOp {op}")
            return red

        return self._build_fused_reduce(proto, shapes, dtypes, mesh, P(axis),
                                        reduce_flat, _jit, plan)

    def _build_broadcast(self, proto, shapes, mesh, axis, world,
                         _jit=jax.jit):
        root = proto.root_rank

        def body(*shards):
            outs = []
            for s in shards:
                x = s.reshape(s.shape[1:])
                idx = lax.axis_index(axis)
                if jnp.issubdtype(x.dtype, jnp.bool_):
                    m = jnp.where(idx == root, x, False)
                    outs.append(lax.psum(m.astype(jnp.int32), axis).astype(jnp.bool_))
                else:
                    m = jnp.where(idx == root, x, jnp.zeros_like(x))
                    outs.append(lax.psum(m, axis))
            return tuple(outs)

        return _jit(shard_map(
            body, mesh=mesh,
            in_specs=tuple(P(axis) for _ in shapes),
            out_specs=tuple(P() for _ in shapes), check_vma=False))

    def _build_hier_broadcast(self, proto, shapes, hmesh, world,
                              _jit=jax.jit):
        """Two-level broadcast: leader exchange (cross/DCN) → intra
        fan-out (local/ICI).

        The root masks everyone else to zero (same trick as the flat
        builder), then ``psum("cross")`` lands the payload on the one
        rank per slice that shares the root's local index (the DCN leg —
        only L-1 slice leaders receive across the slow links), and
        ``psum("local")`` fans it out within each slice over ICI.  Only
        zeros are ever summed with the payload, so the result is
        bitwise-identical to flat for every dtype.
        """
        root = proto.root_rank
        local_size = int(hmesh.devices.shape[1])
        root_cross, root_local = divmod(root, local_size)

        def body(*shards):
            outs = []
            at_root = jnp.logical_and(
                lax.axis_index("cross") == root_cross,
                lax.axis_index("local") == root_local)
            for s in shards:
                x = s.reshape(s.shape[1:])
                if jnp.issubdtype(x.dtype, jnp.bool_):
                    m = jnp.where(at_root, x, False).astype(jnp.int32)
                    m = lax.psum(m, "cross")      # root → slice leaders
                    m = lax.psum(m, "local")      # leaders → slice fan-out
                    outs.append(m.astype(jnp.bool_))
                else:
                    m = jnp.where(at_root, x, jnp.zeros_like(x))
                    m = lax.psum(m, "cross")      # root → slice leaders
                    outs.append(lax.psum(m, "local"))
            return tuple(outs)

        return _jit(shard_map(
            body, mesh=hmesh,
            in_specs=tuple(P(("cross", "local")) for _ in shapes),
            out_specs=tuple(P() for _ in shapes), check_vma=False))

    def _build_allgather(self, proto, shapes, mesh, axis, world,
                         _jit=jax.jit):
        def body(*shards):
            outs = []
            for s in shards:
                x = s.reshape(s.shape[1:])
                outs.append(lax.all_gather(x, axis, axis=0, tiled=True))
            return tuple(outs)

        return _jit(shard_map(
            body, mesh=mesh,
            in_specs=tuple(P(axis) for _ in shapes),
            out_specs=tuple(P() for _ in shapes), check_vma=False))

    def _build_hier_allreduce(self, proto, shapes, dtypes, hmesh, world,
                              _jit=jax.jit, plan=()):
        """Two-level fused allreduce: RS(local) → AR(cross) → AG(local).

        Same fusion/dtype-grouping contract as ``_build_allreduce`` (via the
        shared ``_build_fused_reduce``), but the reduction runs over a
        (cross, local) mesh so bytes over the slow cross links drop by
        1/local_size (reference N17's hierarchical path; SURVEY.md §2c).

        SUM/AVERAGE ride psum_scatter→psum→all_gather; MIN/MAX gather the
        slice, reduce elementwise, and cross only their 1/local shard
        (both exact in any association order, so results are
        bitwise-identical to flat whenever the arithmetic is — min/max
        always, sums for exactly-representable values); ADASUM maps its
        vector-halving-doubling onto the torus axes at both levels
        (``adasum_allreduce_hier``) — halving rounds ride ICI first, only
        the fully-halved shards touch DCN.
        """
        from ..parallel.hierarchical import (hierarchical_allreduce,
                                             hierarchical_allreduce_minmax)
        op = proto.reduce_op

        if op in (C.ReduceOp.MIN, C.ReduceOp.MAX):
            mm = "min" if op == C.ReduceOp.MIN else "max"

            def reduce_flat(flat):
                return hierarchical_allreduce_minmax(flat, mm, "cross",
                                                     "local")
        elif op == C.ReduceOp.ADASUM:
            from ..common.topology import torus_dims
            from ..parallel.adasum import adasum_allreduce_hier
            from ..parallel.topology import hier_bit_orders
            st = self._slice_topology(proto.process_set_id)
            orders = hier_bit_orders(st.local_size, st.num_slices)
            local_bits, cross_bits = orders

            def reduce_flat(flat):
                return adasum_allreduce_hier(flat, "cross", "local",
                                             local_bits=local_bits,
                                             cross_bits=cross_bits)
        else:
            def reduce_flat(flat):
                avg = (op == C.ReduceOp.AVERAGE
                       and jnp.issubdtype(flat.dtype, jnp.floating))
                red = hierarchical_allreduce(flat, "cross", "local",
                                             average=avg)
                if op == C.ReduceOp.AVERAGE and not avg:
                    red = red // world
                return red

        return self._build_fused_reduce(proto, shapes, dtypes, hmesh,
                                        P(("cross", "local")), reduce_flat,
                                        _jit, plan)

    def _build_hier_allgather(self, proto, shapes, hmesh, world,
                              _jit=jax.jit):
        """Two-level allgather: AG(local) → AG(cross).

        Rank order is cross-major × local-minor, matching the flat world
        order (devices are reshaped (cross, local) from the same ordered
        list), so results are byte-identical to the flat path.
        """
        def body(*shards):
            outs = []
            for s in shards:
                x = s.reshape(s.shape[1:])
                x = lax.all_gather(x, "local", axis=0, tiled=True)
                outs.append(lax.all_gather(x, "cross", axis=0, tiled=True))
            return tuple(outs)

        return _jit(shard_map(
            body, mesh=hmesh,
            in_specs=tuple(P(("cross", "local")) for _ in shapes),
            out_specs=tuple(P() for _ in shapes), check_vma=False))

    def _build_reducescatter(self, proto, shapes, mesh, axis, world,
                             _jit=jax.jit):
        op = proto.reduce_op
        if op not in (C.ReduceOp.SUM, C.ReduceOp.AVERAGE, C.ReduceOp.MIN,
                      C.ReduceOp.MAX, C.ReduceOp.PRODUCT):
            raise ValueError(f"reducescatter does not support ReduceOp {op}")

        def body(*shards):
            outs = []
            for s in shards:
                x = s.reshape(s.shape[1:])
                if op in (C.ReduceOp.SUM, C.ReduceOp.AVERAGE):
                    r = lax.psum_scatter(x, axis, scatter_dimension=0,
                                         tiled=True)
                    if op == C.ReduceOp.AVERAGE:
                        r = r / jnp.asarray(world, r.dtype)
                else:
                    # MIN/MAX/PRODUCT: no native scatter-reduce; gather,
                    # reduce elementwise, keep this rank's slice.
                    g = lax.all_gather(x, axis)          # [world, S0, ...]
                    if op == C.ReduceOp.MIN:
                        full = jnp.min(g, axis=0)
                    elif op == C.ReduceOp.MAX:
                        full = jnp.max(g, axis=0)
                    else:
                        full = jnp.prod(g, axis=0)
                    chunk = full.shape[0] // world
                    idx = lax.axis_index(axis)
                    r = lax.dynamic_slice_in_dim(full, idx * chunk, chunk, 0)
                outs.append(r[None])  # re-stack: [1, S0/world, ...]
            return tuple(outs)

        return _jit(shard_map(
            body, mesh=mesh,
            in_specs=tuple(P(axis) for _ in shapes),
            out_specs=tuple(P(axis) for _ in shapes), check_vma=False))

    def _build_alltoall(self, proto, shapes, mesh, axis, world,
                        _jit=jax.jit):
        def body(*shards):
            outs = []
            for s in shards:
                x = s.reshape(s.shape[1:])
                y = lax.all_to_all(x, axis, split_axis=0, concat_axis=0,
                                   tiled=True)
                outs.append(y[None])
            return tuple(outs)

        return _jit(shard_map(
            body, mesh=mesh,
            in_specs=tuple(P(axis) for _ in shapes),
            out_specs=tuple(P(axis) for _ in shapes), check_vma=False))
