"""Logging, mirroring the reference's LOG(level) surface.

Reference: ``horovod/common/logging.cc`` (SURVEY.md §2a N23) —
``HOROVOD_LOG_LEVEL`` in {trace, debug, info, warning, error, fatal},
``HOROVOD_LOG_TIMESTAMP`` toggles timestamps.
"""

from __future__ import annotations

import logging
import os
import sys

_LEVELS = {
    "trace": 5,
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "fatal": logging.CRITICAL,
}

logging.addLevelName(5, "TRACE")

_logger = None


def get_logger() -> logging.Logger:
    global _logger
    if _logger is None:
        _logger = logging.getLogger("horovod_tpu")
        level_name = os.environ.get("HVD_TPU_LOG_LEVEL",
                                    os.environ.get("HOROVOD_LOG_LEVEL", "warning"))
        _logger.setLevel(_LEVELS.get(level_name.strip().lower(), logging.WARNING))
        handler = logging.StreamHandler(sys.stderr)
        ts = os.environ.get("HOROVOD_LOG_TIMESTAMP", "1").lower() not in ("0", "false")
        fmt = "[%(asctime)s] [%(levelname)s] %(message)s" if ts else "[%(levelname)s] %(message)s"
        handler.setFormatter(logging.Formatter(fmt))
        _logger.addHandler(handler)
        _logger.propagate = False
    return _logger
