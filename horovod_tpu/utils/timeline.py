"""Chrome-trace timeline writer.

TPU-native equivalent of the reference's ``horovod/common/timeline.cc``
(SURVEY.md §2a N10): one lane per tensor, with NEGOTIATE / QUEUE /
MEMCPY_IN_FUSION_BUFFER / XLA_ALLREDUCE / ... phase events, activated by
``HOROVOD_TIMELINE=<file>`` and optionally marking coordinator cycles
(``HOROVOD_TIMELINE_MARK_CYCLES``).  Output loads in ``chrome://tracing`` /
Perfetto exactly like the reference's.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, Optional, Union


def per_rank_filename(base: str, rank: Union[int, str]) -> str:
    """THE per-rank suffix scheme for trace/timeline output files.

    Every launch path must produce the same names for the same world —
    ``<base>.<global rank>`` — or the merge tool's glob (``<base>.*``) and
    the docs' examples break on one backend: ``runner/run.py`` suffixes
    with the worker's global rank, ``runner/tpu_vm.py`` with the pod
    worker id (the process's global rank in one-proc-per-host mode), and
    elastic workers suffix at rendezvous time with their assigned rank
    (the driver cannot know ranks before assignment).
    """
    return f"{base}.{rank}"


class Timeline:
    """Thread-safe Chrome trace-event JSON writer.

    Phases mirror the reference's activity names so existing timeline
    tooling reads both: NEGOTIATE_ALLREDUCE, QUEUE, MEMCPY_IN_FUSION_BUFFER,
    XLA_ALLREDUCE (where the reference says NCCL_ALLREDUCE), etc.
    """

    def __init__(self, filename: str = "", mark_cycles: bool = False):
        self._filename = filename
        self._mark_cycles = mark_cycles
        self._fh = None
        self._lock = threading.Lock()
        self._tids: Dict[str, int] = {}
        self._next_tid = 1
        self._start = time.perf_counter()
        self._pending_first = True
        if filename:
            self._fh = open(filename, "w")
            self._fh.write("[\n")
            self._emit({"name": "process_name", "ph": "M", "pid": 0,
                        "args": {"name": "horovod_tpu coordinator"}})

    @property
    def enabled(self) -> bool:
        return self._fh is not None

    def _now_us(self) -> float:
        return (time.perf_counter() - self._start) * 1e6

    def _tid(self, tensor_name: str) -> int:
        tid = self._tids.get(tensor_name)
        if tid is None:
            tid = self._next_tid
            self._next_tid += 1
            self._tids[tensor_name] = tid
            self._emit({"name": "thread_name", "ph": "M", "pid": 0, "tid": tid,
                        "args": {"name": tensor_name}})
        return tid

    def _emit(self, event: dict):
        if self._fh is None:
            return
        with self._lock:
            if not self._pending_first:
                self._fh.write(",\n")
            self._pending_first = False
            self._fh.write(json.dumps(event))

    def start_activity(self, tensor_name: str, activity: str):
        if self._fh is None:
            return
        self._emit({"name": activity, "ph": "B", "pid": 0,
                    "tid": self._tid(tensor_name), "ts": self._now_us()})

    def end_activity(self, tensor_name: str, activity: str = ""):
        if self._fh is None:
            return
        self._emit({"name": activity, "ph": "E", "pid": 0,
                    "tid": self._tid(tensor_name), "ts": self._now_us()})

    def instant(self, name: str, args: Optional[dict] = None):
        if self._fh is None:
            return
        self._emit({"name": name, "ph": "i", "pid": 0, "tid": 0,
                    "ts": self._now_us(), "s": "g", "args": args or {}})

    def counter(self, name: str, values: dict):
        """Chrome-trace counter track (ph="C"): per-cycle scalar series —
        negotiation microseconds, response-cache hit/miss/invalidation
        counts — rendered as stacked area lanes in Perfetto."""
        if self._fh is None:
            return
        self._emit({"name": name, "ph": "C", "pid": 0,
                    "ts": self._now_us(), "args": values})

    def mark_cycle(self, cycle_index: int):
        if self._fh is None or not self._mark_cycles:
            return
        self.instant("CYCLE_START", {"cycle": cycle_index})

    def close(self):
        if self._fh is None:
            return
        with self._lock:
            self._fh.write("\n]\n")
            self._fh.close()
            self._fh = None
