"""Data-loading helpers: rank sharding and async prefetch.

Parity: reference ``horovod/data/data_loader_base.py``
(``AsyncDataLoaderMixin`` — SURVEY.md §2b P13) plus the shard-per-rank
pattern every Horovod example implements by hand
(``DistributedSampler(num_replicas=hvd.size(), rank=hvd.rank())``).

TPU-first additions: ``prefetch_to_device`` overlaps host→HBM transfer with
compute (the TPU analogue of pinned-memory prefetch), and sharding helpers
understand the stacked-global-batch convention used by shard_map train
steps.
"""

from __future__ import annotations

import queue
import threading
from typing import Iterable, Iterator, Optional

import jax
import numpy as np

from ..common import basics


class AsyncDataLoaderMixin:
    """Mix into a loader class to move ``__iter__`` production onto a
    background thread with a bounded prefetch queue.

    Reference-compatible surface: ``async_loader_queue_size`` (0 disables),
    ``close_async_loader()``.  Mix first:
    ``class MyLoader(AsyncDataLoaderMixin, BaseLoader)``.
    """

    def __init__(self, *args, async_loader_queue_size: int = 64, **kwargs):
        self.async_loader_queue_size = async_loader_queue_size
        self._async_queue: Optional[queue.Queue] = None
        self._async_thread: Optional[threading.Thread] = None
        self._async_stop = threading.Event()
        super().__init__(*args, **kwargs)

    def _async_worker(self, q: queue.Queue, stop: threading.Event):
        # q/stop are THIS iteration's, passed by value: a producer that
        # outlives close_async_loader's join can only ever touch its own
        # (abandoned) queue, never a newer iteration's.
        try:
            for item in super().__iter__():
                if stop.is_set():
                    return
                q.put(item)
        except BaseException as exc:  # noqa: BLE001 - surfaced to consumer
            q.put(_Raise(exc))
        finally:
            q.put(_SENTINEL)

    def __iter__(self):
        if self.async_loader_queue_size <= 0:
            yield from super().__iter__()
            return
        self.close_async_loader()
        self._async_stop = threading.Event()
        self._async_queue = queue.Queue(maxsize=self.async_loader_queue_size)
        self._async_thread = threading.Thread(
            target=self._async_worker,
            args=(self._async_queue, self._async_stop), daemon=True)
        self._async_thread.start()
        while True:
            item = self._async_queue.get()
            if item is _SENTINEL:
                break
            if isinstance(item, _Raise):
                raise item.exc
            yield item

    def close_async_loader(self):
        """Stop the background producer (reference API)."""
        if self._async_thread is None:
            return
        self._async_stop.set()
        try:  # unblock a producer stuck on a full queue
            while True:
                self._async_queue.get_nowait()
        except queue.Empty:
            pass
        self._async_thread.join(timeout=10)
        self._async_thread = None


class _Raise:
    def __init__(self, exc):
        self.exc = exc


_SENTINEL = object()


def shard_indices(n: int, rank: Optional[int] = None,
                  size: Optional[int] = None, shuffle: bool = True,
                  seed: int = 0, drop_remainder: bool = True) -> np.ndarray:
    """This rank's sample indices — the ``DistributedSampler`` recipe.

    Every rank gets the SAME number of samples (equal per-rank lengths are
    what keeps per-batch collectives in lockstep): ``drop_remainder=True``
    truncates to ``n // size`` per rank; ``False`` pads by wrapping around,
    exactly like ``torch.utils.data.DistributedSampler``.
    """
    rank = basics.rank() if rank is None else rank
    size = basics.size() if size is None else size
    idx = np.arange(n)
    if shuffle:
        np.random.RandomState(seed).shuffle(idx)
    if drop_remainder:
        per = n // size
        return idx[rank * per:(rank + 1) * per]
    total = -(-n // size) * size  # ceil
    idx = np.concatenate([idx, idx[:total - n]])
    return idx[rank:total:size]


class ShardedBatchIterator:
    """Iterate tuples of numpy arrays as per-rank batches.

    In single-controller SPMD mode yields GLOBAL batches of
    ``batch_size * size()`` rows (feed directly to a shard_map'd step with
    batch-sharded in_specs); in per-process mode yields this rank's local
    ``batch_size`` rows.

    ``drop_remainder=False`` keeps the tail as a short final batch — fine
    for per-process loops and plain jit, but a shard_map'd step with
    batch-sharded in_specs needs full ``batch_size * size()`` batches:
    keep the default ``drop_remainder=True`` there.
    """

    def __init__(self, arrays, batch_size: int, shuffle: bool = True,
                 seed: int = 0, drop_remainder: bool = True):
        self.arrays = [np.asarray(a) for a in arrays]
        n = len(self.arrays[0])
        assert all(len(a) == n for a in self.arrays)
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.drop_remainder = drop_remainder
        self.epoch = 0

    def set_epoch(self, epoch: int):
        self.epoch = epoch

    def _global_batch(self) -> int:
        world = basics.size() if basics.is_initialized() else 1
        return self.batch_size * max(world, 1)

    def __iter__(self):
        from ..ops import eager
        n = len(self.arrays[0])
        if basics.is_initialized() and eager.per_process_mode():
            idx = shard_indices(n, shuffle=self.shuffle,
                                seed=self.seed + self.epoch,
                                drop_remainder=self.drop_remainder)
            bs = self.batch_size
        else:
            idx = np.arange(n)
            if self.shuffle:
                np.random.RandomState(self.seed + self.epoch).shuffle(idx)
            bs = self._global_batch()
        stop = (len(idx) - len(idx) % bs) if self.drop_remainder else len(idx)
        for i in range(0, stop, bs):
            sel = idx[i:i + bs]
            yield tuple(a[sel] for a in self.arrays)

    def _shard_len(self) -> tuple:
        """(per-shard sample count, batch size) exactly as __iter__ uses."""
        from ..ops import eager
        n = len(self.arrays[0])
        if basics.is_initialized() and eager.per_process_mode():
            world = max(basics.size(), 1)
            shard = n // world if self.drop_remainder else -(-n // world)
            return shard, self.batch_size
        return n, self._global_batch()

    def __len__(self):
        shard, bs = self._shard_len()
        return shard // bs if self.drop_remainder else -(-shard // bs)


def prefetch_to_device(iterator: Iterable, size: int = 2,
                       sharding=None) -> Iterator:
    """Overlap host→device transfer with compute: keep ``size`` batches in
    flight as device arrays (``jax.device_put`` is async)."""
    import collections
    buf = collections.deque()

    def put(batch):
        if sharding is not None:
            return jax.tree_util.tree_map(
                lambda x: jax.device_put(x, sharding), batch)
        return jax.tree_util.tree_map(jax.device_put, batch)

    it = iter(iterator)
    try:
        for _ in range(size):
            buf.append(put(next(it)))
    except StopIteration:
        pass
    while buf:
        out = buf.popleft()
        try:
            buf.append(put(next(it)))
        except StopIteration:
            pass
        yield out
