"""Gradient compression for the TF binding.

Parity: reference ``horovod/tensorflow/compression.py`` —
``Compression.none`` / ``Compression.fp16`` with ``compress``/``decompress``
returning a context.  On TPU the natural wire dtype is bfloat16 (fp32
dynamic range, native MXU type), so ``Compression.bf16`` is added; ``fp16``
is kept for API parity.  Operates on the host numpy arrays the binding
bridges through, so the compressed dtype is what crosses into the engine.
"""

from __future__ import annotations

import numpy as np

import ml_dtypes


class Compressor:
    # Cast-style compressors set wire_mode ("bf16"/"fp16") so the binding
    # routes them through the engine's fused wire compression (see
    # jax/compression.py); custom compressors keep the explicit hooks.
    wire_mode = None

    @staticmethod
    def compress(a: np.ndarray):
        raise NotImplementedError

    @staticmethod
    def decompress(a: np.ndarray, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    @staticmethod
    def compress(a: np.ndarray):
        return a, None

    @staticmethod
    def decompress(a: np.ndarray, ctx):
        return a


class _CastCompressor(Compressor):
    wire_dtype: np.dtype

    @classmethod
    def compress(cls, a: np.ndarray):
        if np.issubdtype(a.dtype, np.floating) or a.dtype == ml_dtypes.bfloat16:
            return a.astype(cls.wire_dtype), a.dtype
        return a, None

    @classmethod
    def decompress(cls, a: np.ndarray, ctx):
        return a if ctx is None else a.astype(ctx)


class FP16Compressor(_CastCompressor):
    wire_dtype = np.dtype(np.float16)
    wire_mode = "fp16"


class BF16Compressor(_CastCompressor):
    wire_dtype = np.dtype(ml_dtypes.bfloat16)
    wire_mode = "bf16"


class Compression:
    """Reference-parity namespace: ``Compression.none`` / ``.fp16`` /
    ``.bf16``."""
    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
