"""TensorFlow binding: ``import horovod_tpu.tensorflow as hvd``.

Parity with the reference's TF API (``horovod/tensorflow/__init__.py`` —
SURVEY.md §2b P4): ``DistributedOptimizer``, ``DistributedGradientTape``,
``broadcast_variables``, the collective op surface, compression, plus the
core ``init/rank/size`` re-exports.  Backed by the same background
coordinator (``ops/engine.py``) as the JAX and torch bindings — TF tensors
bridge through host numpy; the data plane stays XLA collectives over the
device mesh.

Graph mode: gradient reductions inside Keras' compiled ``train_step`` run
as ``tf.py_function`` bodies, so out-of-graph negotiation still happens at
step-execution time (the role the reference's ``xla_mpi_ops.cc`` custom
call played — SURVEY.md N28).  For peak TPU throughput prefer the JAX
binding (in-graph ``lax.psum``); this binding is the compatibility surface
for TF/Keras codebases.
"""

from __future__ import annotations

from typing import Optional

import numpy as np
import tensorflow as tf

from ..common import basics
from ..common.basics import (  # noqa: F401  (re-export, reference parity)
    init, shutdown, is_initialized, rank, size, local_rank, local_size,
    cross_rank, cross_size, start_timeline, stop_timeline,
    start_profile, stop_profile, profile_step, add_process_set,
)
from ..common.process_sets import ProcessSet  # noqa: F401
from ..ops import eager
from .compression import Compression  # noqa: F401
from .mpi_ops import (  # noqa: F401
    Adasum, Average, Max, Min, Product, ReduceOp, Sum,
    allgather, allgather_object, allreduce, alltoall, barrier,
    broadcast, broadcast_object,
    graph_safe, grouped_allreduce, join, reducescatter,
)


def _reduce_numpy_list(arrays, name, op, compression, process_set):
    """Shared eager core: compress → ONE grouped allreduce → decompress.

    Cast-style compressors (fp16/bf16) skip the host-side cast pair: the
    engine fuses the wire-dtype casts into the jitted collective program,
    and results come back in the inputs' own dtype."""
    from .mpi_ops import _submit
    # Reverse-registration priority (first variable = highest): the grads
    # the next forward pass needs first lead the coordinator cycle.  The
    # variable order is identical across ranks, so the stamps agree.
    prios = [len(arrays) - i for i in range(len(arrays))]
    wire = getattr(compression, "wire_mode", None)
    if wire is not None:
        outs = eager.grouped_allreduce(
            [_submit(a, process_set) for a in arrays], name=name, op=op,
            process_set=process_set, compression=wire, priorities=prios)
        return [np.asarray(eager.to_local(o)).reshape(a.shape)
                .astype(a.dtype) for o, a in zip(outs, arrays)]
    comp = [compression.compress(a) for a in arrays]
    outs = eager.grouped_allreduce(
        [_submit(c, process_set) for c, _ in comp], name=name, op=op,
        process_set=process_set, priorities=prios)
    return [compression.decompress(
                np.asarray(eager.to_local(o)), ctx).reshape(a.shape)
            for o, (_, ctx), a in zip(outs, comp, arrays)]


def _allreduce_grads(grads, name, op, compression, process_set):
    """Allreduce a (possibly nested, possibly None-holding) gradient
    structure; safe both eagerly and inside a ``tf.function`` trace."""
    flat = tf.nest.flatten(grads)
    idx = [i for i, g in enumerate(flat) if g is not None]
    if not idx:
        return grads
    dense = [tf.convert_to_tensor(flat[i]) for i in idx]

    def _eager_call(*tensors):
        arrays = [t.numpy() for t in tensors]
        outs = _reduce_numpy_list(arrays, name, op, compression, process_set)
        return [tf.constant(np.ascontiguousarray(o), dtype=t.dtype)
                for o, t in zip(outs, tensors)]

    if tf.executing_eagerly():
        reduced = _eager_call(*dense)
    else:
        # Compiled train step: negotiation is out-of-graph, so it runs in a
        # py_function body at step-execution time (reference N28's role).
        reduced = tf.py_function(
            lambda *ts: _eager_call(*ts), dense, [t.dtype for t in dense])
        if not isinstance(reduced, (list, tuple)):
            reduced = [reduced]
        for r, t in zip(reduced, dense):
            r.set_shape(t.shape)
    out = list(flat)
    for i, r in zip(idx, reduced):
        out[i] = r
    return tf.nest.pack_sequence_as(grads, out)


class _DistributedGradientTape:
    """Wraps ``tf.GradientTape`` so ``gradient()`` returns cross-rank
    averaged gradients (reference: ``hvd.DistributedGradientTape``,
    SURVEY.md §3.5)."""

    def __init__(self, tape: tf.GradientTape, compression=Compression.none,
                 op=Average, process_set: Optional[ProcessSet] = None,
                 name: str = "DistributedGradientTape"):
        self._tape = tape
        self._compression = compression
        self._op = op
        self._process_set = process_set
        self._name = name

    def __getattr__(self, item):
        return getattr(self._tape, item)

    def gradient(self, target, sources, output_gradients=None):
        grads = self._tape.gradient(target, sources, output_gradients)
        return _allreduce_grads(grads, f"{self._name}.Allreduce", self._op,
                                self._compression, self._process_set)


def DistributedGradientTape(gradtape: tf.GradientTape,
                            compression=Compression.none,
                            op=Average,
                            process_set: Optional[ProcessSet] = None):
    return _DistributedGradientTape(gradtape, compression=compression,
                                    op=op, process_set=process_set)


def DistributedOptimizer(optimizer, name: Optional[str] = None,
                         compression=Compression.none, op=Average,
                         backward_passes_per_step: int = 1,
                         process_set: Optional[ProcessSet] = None,
                         check=False):
    """Wrap a Keras optimizer so ``apply_gradients`` averages gradients
    across ranks first (reference: ``hvd.DistributedOptimizer`` for TF).

    ``check=True`` lints the calling script for deadlock-prone collective
    patterns at wrap time (``check="strict"`` raises on errors) — see
    ``horovod_tpu.analysis`` and docs/analysis.md.

    Implemented as a dynamic subclass of the optimizer's own class (the
    reference's ``horovod/_keras`` pattern) so Keras ``model.compile``
    type checks still pass.

    ``backward_passes_per_step > 1`` — local gradient aggregation
    (reference: ``horovod/tensorflow/gradient_aggregation_eager.py``):
    gradients accumulate into non-trainable tf.Variables, and only every
    Nth call reduces the accumulated average across ranks and applies it;
    intermediate calls touch no weights and move no bytes, cutting
    communication N×.  N identical micro-batches under bpps=N therefore
    produce exactly one bpps=1 step on the combined batch.
    """
    if check:
        from ..analysis.hooks import run_check_hook
        run_check_hook(check)
    hvd_name = name or f"Distributed{optimizer.__class__.__name__}"

    cls = optimizer.__class__

    class _Distributed(cls):
        _hvd_spec = None

        def _hvd_state(self):
            # Lazy per-instance aggregation state (instances come from
            # from_config, so __init__ customization is off the table).
            if not hasattr(self, "_hvd_agg"):
                self._hvd_agg = {"counter": None, "acc": None}
            return self._hvd_agg

        def _hvd_reduce_apply(self, grads, hvars, args, kwargs):
            spec = type(self)._hvd_spec
            reduced = _allreduce_grads(grads, f"{spec['name']}.Allreduce",
                                       spec["op"], spec["compression"],
                                       spec["process_set"])
            return super().apply_gradients(
                list(zip(reduced, hvars)), *args, **kwargs)

        def apply_gradients(self, grads_and_vars, *args, **kwargs):
            gv = list(grads_and_vars)
            grads = [g for g, _ in gv]
            hvars = [v for _, v in gv]
            spec = type(self)._hvd_spec
            bpps = spec["bpps"]
            if bpps == 1:
                return self._hvd_reduce_apply(grads, hvars, args, kwargs)

            st = self._hvd_state()
            if st["acc"] is None:
                st["counter"] = tf.Variable(0, dtype=tf.int64,
                                            trainable=False,
                                            name=f"{spec['name']}/agg_count")
                st["acc"] = [tf.Variable(tf.zeros_like(v), trainable=False,
                                         name=f"{spec['name']}/agg_{i}")
                             for i, v in enumerate(hvars)]
                # Vars whose grad stayed None the whole window get None at
                # the boundary too (matching bpps=1, which forwards None
                # so e.g. AdamW weight decay skips frozen branches).
                st["seen"] = [False] * len(hvars)
            for i, (a, g) in enumerate(zip(st["acc"], grads)):
                if g is not None:
                    st["seen"][i] = True
                    a.assign_add(tf.cast(g, a.dtype) / float(bpps))
            st["counter"].assign_add(1)

            def _boundary():
                agg = [a.read_value() if st["seen"][i] else None
                       for i, a in enumerate(st["acc"])]
                res = self._hvd_reduce_apply(agg, hvars, args, kwargs)
                for a in st["acc"]:
                    a.assign(tf.zeros_like(a))
                st["seen"] = [False] * len(hvars)
                return res

            if tf.executing_eagerly():
                if int(st["counter"].numpy()) % bpps == 0:
                    return _boundary()
                return None
            # Compiled train step: the skip must be a graph-level cond.
            return tf.cond(
                tf.equal(st["counter"] % bpps, 0),
                lambda: (_boundary(), tf.constant(True))[1],
                lambda: tf.constant(False))

    _Distributed.__name__ = cls.__name__
    _Distributed.__qualname__ = cls.__qualname__
    _Distributed._hvd_spec = dict(name=hvd_name, op=op,
                                  compression=compression,
                                  process_set=process_set,
                                  bpps=int(backward_passes_per_step))
    new_opt = _Distributed.from_config(optimizer.get_config())
    return new_opt


def broadcast_variables(variables, root_rank: int = 0,
                        process_set: Optional[ProcessSet] = None):
    """Assign rank ``root_rank``'s values to every rank's variables
    (reference: ``hvd.broadcast_variables`` — consistent init / restored
    checkpoints across the world)."""
    variables = list(variables)
    if not variables:
        return
    vals = [v.numpy() for v in variables]
    outs = eager.broadcast_pytree(vals, root_rank, process_set=process_set)
    for v, o in zip(variables, outs):
        v.assign(np.asarray(o).reshape(v.shape))
