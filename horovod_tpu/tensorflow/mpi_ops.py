"""TensorFlow binding: collective op surface over the shared engine.

Parity with the reference's TF op layer (``horovod/tensorflow/mpi_ops.py``
backed by ``horovod/tensorflow/mpi_ops.cc`` — SURVEY.md §2a N27, §2b P4):
``allreduce`` / ``grouped_allreduce`` / ``allgather`` / ``broadcast`` /
``alltoall`` / ``reducescatter`` over ``tf.Tensor``/``tf.Variable`` inputs.

TPU-native design: there is no TF custom-kernel shim — TF tensors are
bridged to host numpy and submitted to the same background coordinator
(``ops/engine.py``) the JAX path uses, so negotiation, fusion, response
caching, timeline and stall inspection all apply identically.  The data
plane stays XLA collectives.  The reference's synchronous TF op semantics
are preserved (TF has no ``*_async`` handles — asynchrony lived in TF's
executor, which this binding does not re-create).

Graph mode: ops raise a clear error under ``tf.function`` tracing unless
wrapped — :func:`graph_safe` wraps the eager implementation in
``tf.py_function`` so compiled Keras ``fit`` loops still negotiate
out-of-graph at step-execution time (the reference's N28
``HOROVOD_ENABLE_XLA_OPS`` custom-call played this role inside XLA).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np
import tensorflow as tf

from ..common import basics
from ..common.process_sets import ProcessSet
from ..ops import collectives as C
from ..ops import eager
# SPMD submit conventions shared with the torch binding (one source of
# truth for the single-controller replicate / my-row / ragged forms).
from ..ops.bridge import (submit_numpy as _submit,
                          take_my_row as _take_my_row,
                          ragged_alltoall_numpy as _ragged_alltoall)

ReduceOp = C.ReduceOp
Average = C.ReduceOp.AVERAGE
Sum = C.ReduceOp.SUM
Min = C.ReduceOp.MIN
Max = C.ReduceOp.MAX
Product = C.ReduceOp.PRODUCT
Adasum = C.Adasum

rank = basics.rank
size = basics.size
local_rank = basics.local_rank
local_size = basics.local_size


def _to_numpy(t) -> np.ndarray:
    if isinstance(t, tf.Variable):
        t = t.value()
    if tf.is_tensor(t):
        return t.numpy()
    return np.asarray(t)


def _to_tf(a: np.ndarray, dtype: tf.DType) -> tf.Tensor:
    return tf.constant(np.ascontiguousarray(a), dtype=dtype)


def _dtype_of(tensor, a: np.ndarray) -> tf.DType:
    """The caller's dtype: the tf dtype when given a tf tensor/variable,
    otherwise the numpy array's own dtype (never a silent float32)."""
    if tf.is_tensor(tensor) or isinstance(tensor, tf.Variable):
        return tf.as_dtype(tensor.dtype)
    return tf.as_dtype(a.dtype)


def _check_eager(what: str):
    if not tf.executing_eagerly():
        raise RuntimeError(
            f"hvd.{what} was called inside a tf.function trace; collective "
            f"negotiation is out-of-graph.  Wrap the call with "
            f"horovod_tpu.tensorflow.graph_safe(...) or run the step "
            f"eagerly (run_eagerly=True)")


def allreduce(tensor, name: Optional[str] = None, op: ReduceOp = Average,
              prescale_factor: Optional[float] = None,
              postscale_factor: Optional[float] = None,
              compression=None,
              process_set: Optional[ProcessSet] = None) -> tf.Tensor:
    _check_eager("allreduce")
    from .compression import Compression
    compression = compression or Compression.none
    a = _to_numpy(tensor)
    dtype = _dtype_of(tensor, a)
    comp, ctx = compression.compress(a)
    out = eager.allreduce(_submit(comp, process_set), name=name, op=op,
                          prescale_factor=prescale_factor,
                          postscale_factor=postscale_factor,
                          process_set=process_set)
    res = compression.decompress(np.asarray(eager.to_local(out)), ctx)
    return _to_tf(res.reshape(a.shape), dtype)


def grouped_allreduce(tensors: Sequence, name: Optional[str] = None,
                      op: ReduceOp = Average,
                      process_set: Optional[ProcessSet] = None) -> List[tf.Tensor]:
    _check_eager("grouped_allreduce")
    arrs = [_to_numpy(t) for t in tensors]
    dtypes = [_dtype_of(t, a) for t, a in zip(tensors, arrs)]
    outs = eager.grouped_allreduce(
        [_submit(a, process_set) for a in arrs], name=name, op=op,
        process_set=process_set)
    return [_to_tf(np.asarray(eager.to_local(o)).reshape(a.shape), dt)
            for o, a, dt in zip(outs, arrs, dtypes)]


def allgather(tensor, name: Optional[str] = None,
              process_set: Optional[ProcessSet] = None) -> tf.Tensor:
    _check_eager("allgather")
    a = _to_numpy(tensor)
    dtype = _dtype_of(tensor, a)
    out = eager.allgather(_submit(a, process_set), name=name,
                          process_set=process_set)
    return _to_tf(np.asarray(eager.to_local(out)), dtype)


def broadcast(tensor, root_rank: int = 0, name: Optional[str] = None,
              process_set: Optional[ProcessSet] = None) -> tf.Tensor:
    _check_eager("broadcast")
    a = _to_numpy(tensor)
    dtype = _dtype_of(tensor, a)
    out = eager.broadcast(_submit(a, process_set), root_rank=root_rank,
                          name=name, process_set=process_set)
    return _to_tf(np.asarray(eager.to_local(out)).reshape(a.shape), dtype)


def alltoall(tensor, splits=None, name: Optional[str] = None,
             process_set: Optional[ProcessSet] = None):
    """Even splits: the gathered tensor.  With ``splits``: returns
    ``(output, received_splits)`` (ragged form, same as the torch
    binding)."""
    _check_eager("alltoall")
    a = _to_numpy(tensor)
    dtype = _dtype_of(tensor, a)
    world = process_set.size() if process_set is not None else basics.size()
    if splits is None:
        if a.shape[0] % world != 0:
            raise ValueError(
                f"alltoall with even splits needs dim0 divisible by the "
                f"process set size ({world}); got {tuple(a.shape)}")
        out = eager.alltoall(_submit(a, process_set), name=name,
                             process_set=process_set)
        return _to_tf(_take_my_row(np.asarray(eager.to_local(out))), dtype)
    out, rsp = _ragged_alltoall(a, _to_numpy(splits), name=name,
                                process_set=process_set)
    return _to_tf(out, dtype), tf.constant(np.ascontiguousarray(rsp))


def reducescatter(tensor, name: Optional[str] = None, op: ReduceOp = Sum,
                  process_set: Optional[ProcessSet] = None) -> tf.Tensor:
    _check_eager("reducescatter")
    a = _to_numpy(tensor)
    dtype = _dtype_of(tensor, a)
    world = process_set.size() if process_set is not None else basics.size()
    if a.shape[0] % world != 0:
        raise ValueError(
            f"reducescatter needs dim0 divisible by the process set size "
            f"({world}); got {tuple(a.shape)}")
    out = eager.reducescatter(_submit(a, process_set), name=name, op=op,
                              process_set=process_set)
    return _to_tf(_take_my_row(np.asarray(eager.to_local(out))), dtype)


def graph_safe(fn, output_dtype: tf.DType = tf.float32):
    """Wrap an eager collective call for use inside ``tf.function``.

    Executes ``fn`` as a ``tf.py_function`` at step-execution time — the
    out-of-graph negotiation the reference ran from a TF custom kernel's
    ``ComputeAsync`` (N27) happens in the py_function body here.
    """
    def wrapped(*args):
        def call(*np_args):
            return fn(*np_args)
        return tf.py_function(call, list(args), output_dtype)
    return wrapped


barrier = eager.barrier
join = eager.join
broadcast_object = eager.broadcast_object
allgather_object = eager.allgather_object
