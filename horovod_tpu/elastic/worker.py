"""Worker-side elastic machinery: notification listener + re-rendezvous
bootstrap.

Parity: reference ``horovod/runner/elastic/worker.py``
(``WorkerNotificationService``/``WorkerNotificationManager``) and the worker
half of §3.4's control flow: the driver pings registered workers on host
changes; ``state.commit()``/``check_host_updates()`` turns the ping into a
``HostsUpdatedInterrupt``; on reset the worker long-polls the rendezvous for
a strictly newer generation and re-forms the JAX world.
"""

from __future__ import annotations

import os
import socket
import threading
from typing import Optional

from . import rendezvous as rdv
from .state import HostsUpdatedInterrupt
from ..utils.logging import get_logger

log = get_logger()

# The generation this process is currently participating in; bootstrap
# requests strictly newer on re-init so a stale assignment can't be rejoined.
_current_version: Optional[int] = None
_manager: Optional["WorkerNotificationManager"] = None


def identity() -> str:
    host = os.environ.get("HOROVOD_HOSTNAME", socket.gethostname())
    local_rank = os.environ.get("HOROVOD_LOCAL_RANK", "0")
    return f"{host}:{local_rank}"


class WorkerNotificationService:
    """Tiny TCP listener; driver sends ``HOSTS_UPDATED <version>\\n``."""

    def __init__(self, on_hosts_updated):
        self._on_hosts_updated = on_hosts_updated
        self._sock = socket.socket()
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("0.0.0.0", 0))
        self._sock.listen(8)
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    @property
    def port(self) -> int:
        return self._sock.getsockname()[1]

    def _serve(self):
        while True:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            try:
                # A wedged/half-open driver connection must not block the
                # accept loop forever (timeouts surface as OSError below).
                conn.settimeout(5.0)
                data = conn.makefile().readline().strip()
                if data.startswith("HOSTS_UPDATED"):
                    version = int(data.split()[1]) if " " in data else 0
                    self._on_hosts_updated(version)
            except (OSError, ValueError):
                pass
            finally:
                # Close on EVERY path: timed-out connections would otherwise
                # leak an fd each until accept() itself fails with EMFILE.
                try:
                    conn.close()
                except OSError:
                    pass

    def stop(self):
        try:
            self._sock.close()
        except OSError:
            pass


class WorkerNotificationManager:
    """Registered on elastic ``State`` objects as ``_notification_manager``;
    ``State.commit()`` calls ``raise_if_updated()``."""

    def __init__(self):
        self._lock = threading.Lock()
        self._pending_version: Optional[int] = None
        self._service = WorkerNotificationService(self._notify)
        addr = os.environ.get("HOROVOD_RENDEZVOUS_ADDR")
        port = os.environ.get("HOROVOD_RENDEZVOUS_PORT")
        if addr and port:
            rdv.register_notification_port(addr, int(port), identity(),
                                           self._service.port)

    def _notify(self, version: int):
        with self._lock:
            self._pending_version = version

    def raise_if_updated(self):
        with self._lock:
            v = self._pending_version
            if v is None:
                return
            # A late ping for the generation we already joined is not news.
            if _current_version is not None and v <= _current_version:
                self._pending_version = None
                return
            self._pending_version = None
        raise HostsUpdatedInterrupt()


def attach_notification_manager(state):
    """Idempotently give ``state`` the process-wide notification manager."""
    global _manager
    if _manager is None:
        _manager = WorkerNotificationManager()
    state._notification_manager = _manager
    return _manager


def elastic_bootstrap():
    """Fetch this worker's assignment for the next generation and project it
    into the environment; returns the re-parsed Config.

    Called from ``basics.init()`` when ``HOROVOD_ELASTIC=1``.
    """
    global _current_version
    from ..common.config import Config

    addr = os.environ.get("HOROVOD_RENDEZVOUS_ADDR")
    port = os.environ.get("HOROVOD_RENDEZVOUS_PORT")
    if not addr or not port:
        raise RuntimeError(
            "HOROVOD_ELASTIC=1 but HOROVOD_RENDEZVOUS_ADDR/PORT are not set "
            "(elastic workers must be launched by torovodrun "
            "--host-discovery-script)")
    min_version = 0 if _current_version is None else _current_version + 1
    timeout = float(os.environ.get("HOROVOD_ELASTIC_TIMEOUT", "600"))
    a = rdv.fetch_assignment(addr, int(port), identity(),
                             min_version=min_version, timeout_s=timeout)
    _current_version = int(a["version"])
    log.info("elastic: joined generation %s as rank %s/%s",
             a["version"], a["rank"], a["size"])
    env = {
        "HOROVOD_RANK": str(a["rank"]),
        "HOROVOD_SIZE": str(a["size"]),
        "HOROVOD_LOCAL_RANK": str(a["local_rank"]),
        "HOROVOD_LOCAL_SIZE": str(a["local_size"]),
        "HOROVOD_CROSS_RANK": str(a["cross_rank"]),
        "HOROVOD_CROSS_SIZE": str(a["cross_size"]),
        "HOROVOD_CONTROLLER_ADDR": str(a["controller_addr"]),
        "HOROVOD_CONTROLLER_PORT": str(a["controller_port"]),
        "HOROVOD_CONTROLLER_PORT2": str(a["controller_port2"]),
    }
    os.environ.update(env)
    return Config.from_env()


def teardown_distributed():
    """Tear the JAX world fully down so init() can re-form it with a new
    size — ``jax.distributed.shutdown()`` plus an XLA backend clear
    (SURVEY.md §7 hard-part #3: elastic re-meshing implies re-init +
    recompile; live arrays must already be host-saved via state.commit)."""
    import jax
    from jax._src import distributed as _jdist
    if _jdist.global_state.client is not None:
        try:
            jax.distributed.shutdown()
        except Exception as exc:  # noqa: BLE001 - peers may already be gone
            log.warning("elastic: jax.distributed.shutdown failed: %s", exc)
            _jdist.global_state.client = None
    import jax.extend.backend as jeb
    jeb.clear_backends()
