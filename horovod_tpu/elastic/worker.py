"""Worker-side elastic machinery: notification listener + re-rendezvous
bootstrap.

Parity: reference ``horovod/runner/elastic/worker.py``
(``WorkerNotificationService``/``WorkerNotificationManager``) and the worker
half of §3.4's control flow: the driver pings registered workers on host
changes; ``state.commit()``/``check_host_updates()`` turns the ping into a
``HostsUpdatedInterrupt``; on reset the worker long-polls the rendezvous for
a strictly newer generation and re-forms the JAX world.
"""

from __future__ import annotations

import atexit
import os
import socket
import sys
import threading
from typing import Optional

from . import rendezvous as rdv
from ..common.exceptions import DrainRequested, HostsUpdatedInterrupt
from ..utils.logging import get_logger

log = get_logger()

# Heartbeat failure detection is effectively DISABLED in elastic jax worlds
# (one "missed heartbeat" per 10s; ~4 months of tolerance): the coordinator
# control plane (protocol v4 — csrc/coordinator.cc) detects a dead rank in
# milliseconds and the elastic driver owns recovery, while the XLA
# coordination service's own detector can only abort() the process (its
# missed-heartbeat / polled-error handlers terminate, and its shutdown
# barrier can never complete once a peer died uncleanly).  See
# docs/fault_tolerance.md "why the jax world is parked, not shut down".
_HEARTBEAT_FOREVER = 1_000_000

# Poisoned generations' native (client, service, preemption-manager)
# triples.  Their threads cannot be stopped — stopping requires the
# cooperative shutdown barrier the dead peer will never join — so they are
# parked here, idling harmlessly, for the remainder of the process.
_parked_worlds: list = []
_exit_guard = {"installed": False, "code": 0, "in_finale": False}

# True only when init_distributed_resilient managed to neutralize the
# coordination service's heartbeat detection.  Parking a world whose
# detectors are still ENABLED is worse than useless — the parked client's
# missed-heartbeat handler would abort() the surviving process ~100s
# after the crash — so teardown_distributed only parks when this is set
# and otherwise degrades to the graceful shutdown path.
_heartbeats_neutralized = False

# The generation this process is currently participating in; bootstrap
# requests strictly newer on re-init so a stale assignment can't be rejoined.
_current_version: Optional[int] = None
_manager: Optional["WorkerNotificationManager"] = None


def _mark_draining() -> None:
    """Flip this rank's monitor readiness to NotReady the moment a driver
    DRAIN ping lands (ISSUE 19: readiness split from liveness).

    The drain itself is consumed later — at the next ``state.commit()``
    via ``raise_if_updated()`` — but the load balancer must stop routing
    NEW requests to a cordoned replica immediately, not at the next
    commit boundary.  Lazy import + best-effort: worker.py stays
    importable jax-free, and a fleet without the monitor (or before
    ``init()``) simply has no readiness surface to flip."""
    try:
        from ..common import basics
        agent = basics._get_state().monitor
        if agent is not None:
            agent.set_ready(False, "draining: driver cordon ping received")
    except Exception:  # noqa: BLE001 - telemetry must never block a drain
        pass


def identity() -> str:
    host = os.environ.get("HOROVOD_HOSTNAME", socket.gethostname())
    local_rank = os.environ.get("HOROVOD_LOCAL_RANK", "0")
    return f"{host}:{local_rank}"


class WorkerNotificationService:
    """Tiny TCP listener; driver sends ``HOSTS_UPDATED <version>\\n``,
    the autoscaler's drain path ``DRAIN\\n``, or — checkpoint pacing
    (ISSUE 12) — ``COMMIT\\n``, the driver's request that the worker
    commit its elastic state NOW because a scale/preemption decision is
    imminent (committing on the timer would race the world change)."""

    def __init__(self, on_hosts_updated, on_drain=None, on_commit=None):
        self._on_hosts_updated = on_hosts_updated
        self._on_drain = on_drain
        self._on_commit = on_commit
        self._sock = socket.socket()
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("0.0.0.0", 0))
        self._sock.listen(8)
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    @property
    def port(self) -> int:
        return self._sock.getsockname()[1]

    def _serve(self):
        while True:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            try:
                # A wedged/half-open driver connection must not block the
                # accept loop forever (timeouts surface as OSError below).
                conn.settimeout(5.0)
                data = conn.makefile().readline().strip()
                if data.startswith("HOSTS_UPDATED"):
                    version = int(data.split()[1]) if " " in data else 0
                    self._on_hosts_updated(version)
                elif data.startswith("DRAIN") and self._on_drain is not None:
                    self._on_drain()
                elif data.startswith("COMMIT") and \
                        self._on_commit is not None:
                    self._on_commit()
                    # Receipt ack (ISSUE 14 bugfix): the driver records
                    # WHICH workers took the paced-commit request and the
                    # preempt drain waits (grace-bounded) for these acks
                    # before cordoning — a drain can no longer race a
                    # commit ping that never arrived.  Old drivers close
                    # without reading; the failed send is harmless.
                    try:
                        conn.sendall(b"ACK\n")
                    except OSError:
                        pass
            except (OSError, ValueError):
                pass
            finally:
                # Close on EVERY path: timed-out connections would otherwise
                # leak an fd each until accept() itself fails with EMFILE.
                try:
                    conn.close()
                except OSError:
                    pass

    def stop(self):
        try:
            self._sock.close()
        except OSError:
            pass


class WorkerNotificationManager:
    """Registered on elastic ``State`` objects as ``_notification_manager``;
    ``State.commit()`` calls ``raise_if_updated()``."""

    def __init__(self):
        self._lock = threading.Lock()
        self._pending_version: Optional[int] = None
        self._drain_pending = False
        self._commit_pending = False
        self._service = WorkerNotificationService(
            self._notify, on_drain=self._notify_drain,
            on_commit=self._notify_commit)
        addr = os.environ.get("HOROVOD_RENDEZVOUS_ADDR")
        port = os.environ.get("HOROVOD_RENDEZVOUS_PORT")
        if addr and port:
            rdv.register_notification_port(addr, int(port), identity(),
                                           self._service.port)

    def _notify(self, version: int):
        with self._lock:
            self._pending_version = version

    def _notify_drain(self):
        with self._lock:
            self._drain_pending = True
        _mark_draining()

    def _notify_commit(self):
        with self._lock:
            self._commit_pending = True

    def consume_commit_request(self) -> bool:
        """True exactly once per driver ``COMMIT`` ping (checkpoint
        pacing, ISSUE 12): the driver is about to execute a scale or
        preemption decision and wants the elastic state committed NOW,
        not at the next timer tick.  Train loops with a periodic commit
        cadence consult ``state.should_commit()`` (which reads this)
        alongside their own schedule."""
        with self._lock:
            pending = self._commit_pending
            self._commit_pending = False
            return pending

    def raise_if_updated(self):
        with self._lock:
            drain = self._drain_pending
            v = self._pending_version
            if drain:
                # Drain outranks a host update: this worker is LEAVING —
                # re-rendezvousing into the next generation first would
                # just delay the departure the driver is waiting on.
                self._drain_pending = False
                self._pending_version = None
            elif v is None:
                return
            # A late ping for the generation we already joined is not news.
            elif _current_version is not None and v <= _current_version:
                self._pending_version = None
                return
            else:
                self._pending_version = None
        if drain:
            raise DrainRequested()
        raise HostsUpdatedInterrupt()


def attach_notification_manager(state):
    """Idempotently give ``state`` the process-wide notification manager."""
    global _manager
    if _manager is None:
        _manager = WorkerNotificationManager()
    state._notification_manager = _manager
    return _manager


def elastic_bootstrap():
    """Fetch this worker's assignment for the next generation and project it
    into the environment; returns the re-parsed Config.

    Called from ``basics.init()`` when ``HOROVOD_ELASTIC=1``.
    """
    global _current_version
    from ..common.config import Config

    addr = os.environ.get("HOROVOD_RENDEZVOUS_ADDR")
    port = os.environ.get("HOROVOD_RENDEZVOUS_PORT")
    if not addr or not port:
        raise RuntimeError(
            "HOROVOD_ELASTIC=1 but HOROVOD_RENDEZVOUS_ADDR/PORT are not set "
            "(elastic workers must be launched by torovodrun "
            "--host-discovery-script)")
    min_version = 0 if _current_version is None else _current_version + 1
    timeout = float(os.environ.get("HOROVOD_ELASTIC_TIMEOUT", "600"))
    a = rdv.fetch_assignment(addr, int(port), identity(),
                             min_version=min_version, timeout_s=timeout)
    _current_version = int(a["version"])
    log.info("elastic: joined generation %s as rank %s/%s",
             a["version"], a["rank"], a["size"])
    env = {
        "HOROVOD_RANK": str(a["rank"]),
        "HOROVOD_SIZE": str(a["size"]),
        "HOROVOD_LOCAL_RANK": str(a["local_rank"]),
        "HOROVOD_LOCAL_SIZE": str(a["local_size"]),
        "HOROVOD_CROSS_RANK": str(a["cross_rank"]),
        "HOROVOD_CROSS_SIZE": str(a["cross_size"]),
        "HOROVOD_CONTROLLER_ADDR": str(a["controller_addr"]),
        "HOROVOD_CONTROLLER_PORT": str(a["controller_port"]),
        "HOROVOD_CONTROLLER_PORT2": str(a["controller_port2"]),
    }
    # Hierarchical control plane × elastic (ISSUE 12): the driver
    # allocates ONE stable agent port per host and ships it with every
    # generation's assignment, so the generation-surviving HostAgent keeps
    # its listen socket across re-rendezvous.
    if a.get("agent_port"):
        env["HOROVOD_AGENT_PORT"] = str(a["agent_port"])
    os.environ.update(env)
    cfg = Config.from_env()
    # Per-rank output suffixing, unified with the static launch paths
    # (utils.timeline.per_rank_filename): the env carries the BASE name
    # (the driver can't know ranks before assignment, and re-suffixing an
    # already-suffixed env value across generations would compound), so
    # the assigned rank is applied to the parsed config only.
    from ..utils.timeline import per_rank_filename
    if cfg.timeline_filename:
        cfg.timeline_filename = per_rank_filename(cfg.timeline_filename,
                                                  a["rank"])
    if cfg.trace_filename:
        cfg.trace_filename = per_rank_filename(cfg.trace_filename,
                                               a["rank"])
    return cfg


def init_distributed_resilient(coordinator_address: str,
                               num_processes: int, process_id: int):
    """Form the jax world for an ELASTIC job with the coordination
    service's own failure detection neutralized.

    The stock client/service abort the whole process when a peer stops
    heartbeating (their missed-heartbeat and error-polling handlers call
    terminate, and Python-level callbacks are not usable on this jaxlib)
    — which would kill the SURVIVORS of a worker loss ~100s after the
    crash, exactly the processes elastic recovery exists to save.  Our
    control plane detects the death in milliseconds (protocol v4 typed
    ABORT → PeerFailureError) and the elastic driver re-forms the world,
    so the jax-level detector is set to effectively-never and the
    poisoned world is parked at teardown (``teardown_distributed``
    with ``abrupt=True``)."""
    global _heartbeats_neutralized
    from jax._src import distributed as _jdist
    try:
        _jdist.global_state.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            service_max_missing_heartbeats=_HEARTBEAT_FOREVER,
            client_max_missing_heartbeats=_HEARTBEAT_FOREVER)
        _heartbeats_neutralized = True
    except TypeError:
        # Signature drift on a newer jax: fall back to the stock init —
        # heartbeat detection stays ENABLED, so abrupt teardowns must
        # degrade to the graceful path (teardown_distributed checks the
        # flag; parking a detecting world would let its missed-heartbeat
        # handler abort() this process later).
        _heartbeats_neutralized = False
        import jax
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes, process_id=process_id)


def _install_exit_guard():
    """After an abrupt teardown the process must end via ``os._exit``:
    interpreter finalization would destroy a parked world's service,
    which cancels the parked client's outstanding poll RPC and that
    client's (unstoppable) thread aborts the whole process from C++ —
    turning a clean exit into rc=134 AFTER all Python work succeeded.
    The guard runs the full runtime shutdown itself, runs the remaining
    (earlier-registered) atexit hooks, then skips interpreter
    finalization.  The true exit code is preserved: an uncaught
    exception exits 1 (the wrapped excepthook records it; 130 for
    KeyboardInterrupt, per convention), and ``sys.exit(n)`` exits ``n``
    — uncaught SystemExit never reaches ``sys.excepthook``, so the code
    is recorded by wrapping ``sys.exit`` itself (which also covers
    argparse errors and ``sys.exit(main())``; a bare ``raise
    SystemExit(n)`` is the one path not covered)."""
    if _exit_guard["installed"]:
        return
    _exit_guard["installed"] = True
    orig_hook = sys.excepthook
    orig_exit = sys.exit

    def record_failure(tp, val, tb):
        orig_hook(tp, val, tb)
        _exit_guard["code"] = 130 if tp is KeyboardInterrupt else 1

    def recording_exit(code=None):
        if code is None:
            _exit_guard["code"] = 0
        elif isinstance(code, int):
            _exit_guard["code"] = code
        else:
            # CPython prints a non-int code to stderr and exits 1.
            _exit_guard["code"] = 1
        orig_exit(code)

    sys.excepthook = record_failure
    sys.exit = recording_exit

    def finale():
        # From here on the latched code IS the exit status: the clean-
        # shutdown clear below must not touch it (finale's own
        # basics.shutdown() call would otherwise zero a real sys.exit(n)).
        _exit_guard["in_finale"] = True
        try:
            from ..common import basics
            basics.shutdown()
        except Exception:  # noqa: BLE001 - exiting anyway
            pass
        try:
            # Run the hooks registered BEFORE the guard (coverage
            # writers, tempfile cleanup, ...): os._exit would silently
            # skip them.  finale is unregistered first so the re-entrant
            # drain cannot recurse.  (A hook registered AFTER the fault
            # runs twice — interpreter drain then this one — rare, and
            # preferable to skipping every startup-registered writer.)
            atexit.unregister(finale)
            atexit._run_exitfuncs()
        except Exception:  # noqa: BLE001 - exiting anyway
            pass
        try:
            sys.stdout.flush()
            sys.stderr.flush()
        except Exception:  # noqa: BLE001 - exiting anyway
            pass
        os._exit(_exit_guard["code"])

    # Registered at fault time, so this runs FIRST among atexit hooks;
    # it drains the earlier ones itself before os._exit.
    atexit.register(finale)


def exit_guard_note_clean_shutdown():
    """Clear a stale exit-code latch on an explicit, successful shutdown.

    ``sys.exit(n)`` latches ``n`` at call time (uncaught SystemExit never
    reaches ``sys.excepthook``), but a *caught* SystemExit — argparse's
    ``parser.exit`` inside ``try/except SystemExit``, a recovered CLI
    helper — leaves the latch stale, and finale would end an otherwise
    healthy run with ``os._exit(n)``.  An explicit ``basics.shutdown()``
    is the "run completed" signal, so it resets the latch; a LATER
    uncaught ``sys.exit(n)`` or exception re-latches the real code.
    No-op from finale itself, where the latch is the exit status.  (A
    caught-and-recovered ``sys.exit`` in a run that never calls
    ``shutdown()`` explicitly remains uncovered.)"""
    if not _exit_guard["in_finale"]:
        _exit_guard["code"] = 0


def teardown_distributed(abrupt: bool = False):
    """Tear the JAX world fully down so init() can re-form it with a new
    size — ``jax.distributed.shutdown()`` plus an XLA backend clear
    (SURVEY.md §7 hard-part #3: elastic re-meshing implies re-init +
    recompile; live arrays must already be host-saved via state.commit).

    ``abrupt=True`` (a control-plane fault declared a peer dead): the
    cooperative shutdown barrier can never complete — the dead rank will
    not join it — and on this jax the failed barrier path ABORTS the
    surviving process.  Instead the poisoned world's native objects are
    parked (their threads idle harmlessly: heartbeat detection was
    disabled by ``init_distributed_resilient``) and the exit guard is
    installed; ``init()`` then forms the next generation on fresh ports.
    """
    import jax
    from jax._src import distributed as _jdist
    gs = _jdist.global_state
    if abrupt and gs.client is not None and not _heartbeats_neutralized:
        # The world was formed by the stock-init fallback: its heartbeat
        # detectors are live, so a parked client would abort() us later.
        # Best effort graceful teardown instead (the try/except below
        # tolerates the barrier failing against the dead peer).
        log.warning("elastic: abrupt teardown requested but heartbeat "
                    "detection could not be neutralized at init; "
                    "degrading to the graceful shutdown path")
        abrupt = False
    if abrupt and gs.client is not None:
        import jax.extend.backend as jeb
        jeb.clear_backends()   # drops the backends' refs into the old world
        _parked_worlds.append((gs.client, gs.service,
                               gs.preemption_sync_manager))
        gs.client = None
        gs.service = None
        gs.preemption_sync_manager = None
        gs.coordinator_address = None
        _install_exit_guard()
        log.warning("elastic: parked the failed generation's jax world "
                    "(%d parked total); re-init will start a fresh one",
                    len(_parked_worlds))
        return
    if gs.client is not None:
        try:
            jax.distributed.shutdown()
        except Exception as exc:  # noqa: BLE001 - peers may already be gone
            log.warning("elastic: jax.distributed.shutdown failed: %s", exc)
            gs.client = None
    import jax.extend.backend as jeb
    jeb.clear_backends()
