"""The elastic driver: discovery polling, rank assignment, worker lifecycle.

Parity: reference ``horovod/runner/elastic/driver.py`` (``ElasticDriver``)
wired into ``horovodrun --min-np/--max-np --host-discovery-script``
(SURVEY.md §2b P10, §3.4): poll the discovery script, maintain the worker
registry and host blacklist, assign ranks, publish versioned rendezvous
generations, notify running workers of host changes, spawn/terminate worker
processes, and decide job success/failure against ``--min-np``.

TPU mapping (SURVEY.md §5): a "host" is a TPU-VM worker; discovery's
production source is the metadata service + preemption notices; losing a
host invalidates the ICI mesh, so a generation change means the surviving
workers re-init the JAX world (see ``worker.teardown_distributed``).
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

from .discovery import DiscoveredHost, HostDiscovery, HostDiscoveryScript
from .registration import WorkerStateRegistry
from .rendezvous import RendezvousServer
from ..utils.logging import get_logger

log = get_logger()


from ..common.net import free_ports as _free_ports  # noqa: E402
from ..common.net import is_local_host, remote_ports  # noqa: E402


class ElasticDriver:
    # When True, every generation change kills and respawns ALL workers —
    # even survivors — instead of only replacing exited ones.  The process
    # path keeps this False (surviving workers re-rank in place by
    # long-polling the versioned rendezvous); executors whose workers are
    # one-shot closures with env baked at spawn (Ray actors) set it True
    # because their workers cannot pick up a new world without a restart.
    respawn_on_generation = False

    def __init__(self, discovery: HostDiscovery, command: List[str],
                 min_np: int, max_np: Optional[int] = None,
                 env: Optional[Dict[str, str]] = None,
                 discovery_interval_s: float = 1.0,
                 start_timeout_s: float = 600.0,
                 rendezvous_addr: Optional[str] = None,
                 output_filename: Optional[str] = None,
                 verbose: int = 0,
                 discovery_grace_s: Optional[float] = None,
                 autoscale_policy=None,
                 autoscale_interval_s: float = 5.0,
                 autoscale_source=None,
                 scale_command: Optional[str] = None,
                 preempt_grace_s: float = 30.0):
        self.discovery = discovery
        self.command = command
        self.min_np = min_np
        self.max_np = max_np
        self.extra_env = dict(env or {})
        self.discovery_interval_s = discovery_interval_s
        self.start_timeout_s = start_timeout_s
        self.output_filename = output_filename
        self.verbose = verbose
        # Discovery-flap debounce: a host must stay MISSING from discovery
        # for this long before the driver drops it from the world.  One
        # bad poll (script hiccup, metadata blip) must not churn rank
        # assignments — appearing hosts still join immediately.  Default:
        # two polls' worth.
        self.discovery_grace_s = (2.0 * discovery_interval_s
                                  if discovery_grace_s is None
                                  else max(0.0, float(discovery_grace_s)))
        # Closed-loop autoscaling (docs/elastic.md): a ScalePolicy consumes
        # summaries from `autoscale_source` (default: rank 0's monitor
        # /health endpoint) and this driver executes the decisions —
        # scale_out through the operator's `scale_command`, evict/scale_in
        # through the drain pipeline (DRAIN ping → worker finishes its
        # batch → clean LEAVE → exit 0 → cordoned host leaves the world).
        self.autoscale_policy = autoscale_policy
        self.autoscale_interval_s = max(0.5, float(autoscale_interval_s))
        self._autoscale_source = autoscale_source
        self.scale_command = scale_command
        self.events: List[dict] = []    # executed decisions, for operators
                                        # and the scenario acceptance test
        # Preemption-driven drains (ISSUE 12): a discovery preemption
        # notice gets the DRAIN → clean LEAVE → cordon path, grace-bounded
        # — a worker still alive past preempt_grace_s is terminated (the
        # legacy sever), still classified as a departure.
        self.preempt_grace_s = max(0.0, float(preempt_grace_s))
        # Hosts cordoned BECAUSE of a preemption notice: released when the
        # notice clears (recreated preemptible hardware under the same
        # address must be able to rejoin), unlike evict cordons, which
        # persist.  Doubles as the handled-once marker: a cordoned host is
        # never re-drained while its notice stands.
        self._preempt_cordoned: set = set()
        self._drain_deadlines: Dict[str, float] = {}
        # Hierarchical control plane × elastic (ISSUE 12): when the worker
        # env arms HOROVOD_HIERARCHICAL_CONTROLLER, the driver allocates
        # ONE stable agent port per host — reused across generations, so
        # the generation-surviving HostAgent keeps its listen socket —
        # and ships it with every assignment.
        raw_hier = (self.extra_env.get("HOROVOD_HIERARCHICAL_CONTROLLER")
                    or os.environ.get("HVD_TPU_HIERARCHICAL_CONTROLLER")
                    or os.environ.get("HOROVOD_HIERARCHICAL_CONTROLLER")
                    or "")
        # The launcher's own environment counts too: workers inherit it
        # through _worker_env, so the driver must allocate stable agent
        # ports whenever the workers will run hierarchical — not only
        # when the CLI flag put the knob into extra_env.
        self._hier = str(raw_hier).strip().lower() in (
            "1", "true", "yes", "on")
        self._agent_ports: Dict[str, int] = {}

        self.registry = WorkerStateRegistry()
        self.rendezvous = RendezvousServer()
        # Explicit address wins; otherwise picked per generation: loopback
        # for all-local worlds, a routable driver address once any worker
        # is remote (a remote worker long-polling ITS OWN loopback for
        # assignments would hang until the start timeout).
        self._rdv_addr_explicit = rendezvous_addr
        self._rdv_addr = rendezvous_addr or "127.0.0.1"
        self._procs: Dict[str, subprocess.Popen] = {}
        self._hosts: List[DiscoveredHost] = []
        self._assigned: Dict[str, dict] = {}
        # Identities the driver itself terminated (host removed / shrunk):
        # their nonzero exit must not blacklist the host as a failure.
        self._released: set = set()
        # Identities the autoscaler asked to drain: their exit 0 is a
        # clean departure (record_left), never the job-success signal.
        self._draining: set = set()
        # Hosts the autoscaler retired (straggler evict / scale-in):
        # excluded from assignment like the blacklist, but clean — an
        # operator scale-out may un-cordon by naming them again through
        # `scale_command` + discovery.
        self._cordoned: set = set()
        # Discovery-flap debounce state: hostname -> (last_seen_monotonic,
        # last_known_slots).
        self._last_seen: Dict[str, tuple] = {}
        self._out_files: Dict[str, tuple] = {}  # identity -> open log files
        self._success = threading.Event()
        self._first_failure_rc = 0

    # ----------------------------------------------------------- assignment
    def active_hosts(self, discovered: List[DiscoveredHost]) -> List[DiscoveredHost]:
        return [h for h in discovered
                if not self.registry.is_blacklisted(h.hostname)
                and h.hostname not in self._cordoned]

    def _effective_hosts(self, discovered: List[DiscoveredHost],
                         now: float) -> List[DiscoveredHost]:
        """Discovery-flap debounce: the discovered set, plus hosts that
        vanished less than ``discovery_grace_s`` ago (kept at their last
        known slot count, in their original order — rank assignments must
        not churn when a host misses ONE poll and returns).  New hosts
        join immediately; blacklist/cordon filtering happens in
        ``active_hosts`` as usual."""
        for h in discovered:
            self._last_seen[h.hostname] = (now, h.slots)
        present = {h.hostname for h in discovered}
        out = list(discovered)
        for name, (seen, slots) in list(self._last_seen.items()):
            if name in present:
                continue
            if now - seen <= self.discovery_grace_s:
                out.append(DiscoveredHost(name, slots))
            else:
                del self._last_seen[name]
        # Deterministic order: the ORIGINAL first-seen order is what keeps
        # assignments stable across flaps (a host re-listed after its
        # one-poll absence must land back on its old ranks); hosts with no
        # previous position — the whole first generation, and any batch of
        # newcomers — keep their DISCOVERY order, preserving the
        # documented hostfile-order rank/coordinator placement.
        order = {h.hostname: i for i, h in enumerate(self._hosts)}
        base = len(order)
        disc_pos = {h.hostname: i for i, h in enumerate(discovered)}
        out.sort(key=lambda h: order.get(
            h.hostname, base + disc_pos.get(h.hostname, 0)))
        return out

    def compute_assignments(self, hosts: List[DiscoveredHost]) -> Dict[str, dict]:
        """Identity → assignment for one generation.  Rank order follows
        host order then local rank (the reference's hostfile-order rule);
        host 0 carries the coordinator."""
        slots = [(h.hostname, lr) for h in hosts for lr in range(h.slots)]
        if self.max_np is not None:
            slots = slots[:self.max_np]
        if len(slots) < self.min_np:
            return {}
        size = len(slots)
        hosts_in_use = []
        for hn, _ in slots:
            if hn not in hosts_in_use:
                hosts_in_use.append(hn)
        local_sizes = {hn: sum(1 for h, _ in slots if h == hn)
                       for hn in hosts_in_use}
        coord_host = ("127.0.0.1" if hosts_in_use[0] in ("localhost",
                                                         "127.0.0.1")
                      else hosts_in_use[0])
        # The controller binds on host 0, not on the driver: bind-probing is
        # only meaningful when they are the same machine.  For a remote host
        # 0 pick from a high range instead (seeded by generation so retries
        # move on); a collision there surfaces as a worker failure and the
        # next generation picks different ports.
        # Hierarchical control plane: one STABLE agent port per host,
        # allocated on the host's first generation and reused for every
        # later one — the generation-surviving HostAgent holds the listen
        # socket across re-rendezvous, so the port must never churn.
        # New LOCAL agent ports are allocated in the SAME free_ports call
        # as the controller ports: probing them separately would close
        # the controller probes first, and the kernel may hand the agent
        # the just-freed controller port — a same-process EADDRINUSE on
        # the rank-0 host.  (Already-cached agent ports can't collide:
        # their agents still hold the listeners, so free_ports skips
        # them.)
        new_local_agents = []
        if self._hier:
            for hn in hosts_in_use:
                if hn not in self._agent_ports:
                    if is_local_host(hn):
                        new_local_agents.append(hn)
                    else:
                        (ap,) = remote_ports(
                            1, 7919 + len(self._agent_ports))
                        self._agent_ports[hn] = ap
        if is_local_host(coord_host):
            ports = _free_ports(2 + len(new_local_agents))
            p1, p2 = ports[0], ports[1]
            for hn, ap in zip(new_local_agents, ports[2:]):
                self._agent_ports[hn] = ap
        else:
            p1, p2 = remote_ports(2, self.rendezvous.version + 1)
            for hn in new_local_agents:
                (ap,) = _free_ports(1)
                self._agent_ports[hn] = ap
        assignments = {}
        for rank, (hn, lr) in enumerate(slots):
            assignments[f"{hn}:{lr}"] = {
                "rank": rank, "size": size,
                "local_rank": lr, "local_size": local_sizes[hn],
                "cross_rank": hosts_in_use.index(hn),
                "cross_size": len(hosts_in_use),
                "controller_addr": coord_host,
                "controller_port": p1, "controller_port2": p2,
                "hostname": hn,
            }
            if self._hier:
                assignments[f"{hn}:{lr}"]["agent_port"] = \
                    self._agent_ports[hn]
        return assignments

    # ------------------------------------------------------------ lifecycle
    def _worker_env(self, identity: str, hostname: str, local_rank: int):
        from ..runner.run import platform_worker_env
        env = dict(os.environ)
        env.update(self.extra_env)
        env.update(platform_worker_env(env))
        env.update({
            "HOROVOD_ELASTIC": "1",
            "HOROVOD_HOSTNAME": hostname,
            "HOROVOD_LOCAL_RANK": str(local_rank),
            "HOROVOD_RENDEZVOUS_ADDR": self._rdv_addr,
            "HOROVOD_RENDEZVOUS_PORT": str(self.rendezvous.port),
        })
        return env

    def _spawn(self, identity: str, assignment: dict):
        hostname = assignment["hostname"]
        env = self._worker_env(identity, hostname, assignment["local_rank"])
        stdout = stderr = None
        if self.output_filename:
            d = os.path.join(self.output_filename, identity.replace(":", "."))
            os.makedirs(d, exist_ok=True)
            # Append so respawns across generations extend one log; handles
            # are tracked and closed when the process is reaped.
            stdout = open(os.path.join(d, "stdout"), "a")
            stderr = open(os.path.join(d, "stderr"), "a")
            self._close_out_files(identity)
            self._out_files[identity] = (stdout, stderr)
        if is_local_host(hostname):
            # is_local_host (not a literal tuple): loopback aliases like
            # 127.0.0.2 — how tests and single-box deployments model
            # multi-host worlds — must spawn locally, not through ssh.
            proc = subprocess.Popen(self.command, env=env,
                                    stdout=stdout, stderr=stderr)
        else:
            from ..runner.run import ssh_command
            hvd_env = {k: v for k, v in env.items()
                       if k.startswith("HOROVOD_")}
            cmd = ssh_command(hostname, hvd_env, self.command)
            proc = subprocess.Popen(cmd, env=dict(os.environ),
                                    stdout=stdout, stderr=stderr)
        self._procs[identity] = proc
        self.registry.record_ready(identity)
        if self.verbose:
            log.warning("elastic driver: spawned %s (pid %s)", identity,
                        proc.pid)

    def _notify_workers(self, version: int):
        from ..common.net import retry_with_backoff
        ports = self.rendezvous.notification_ports()
        for identity, port in ports.items():
            if identity not in self._procs:
                continue
            host = identity.rsplit(":", 1)[0]
            addr = "127.0.0.1" if is_local_host(host) else host

            def _ping(addr=addr, port=port):
                # Per-attempt timeout sized so ALL attempts + backoff stay
                # inside the old single-attempt 5s budget: the notify loop
                # is serial, and it runs during exactly the host-failure
                # events that make workers unreachable — one dead worker
                # must not stall the re-rendezvous rollout for the rest.
                with socket.create_connection((addr, port), timeout=1.5) as s:
                    s.sendall(f"HOSTS_UPDATED {version}\n".encode())

            # Bounded retries with backoff + jitter: a worker mid-GC /
            # briefly partitioned must still learn about the host change
            # (a single 5s attempt used to warn-and-drop, leaving the
            # worker training against a dead generation until its next
            # commit raced the rendezvous).  Still best-effort after the
            # final attempt — the versioned rendezvous long-poll is the
            # correctness backstop; the ping is the latency optimization.
            try:
                retry_with_backoff(
                    _ping, retries=2, base_ms=200.0, max_ms=2000.0,
                    on_retry=lambda a, exc, d: log.info(
                        "elastic driver: notify %s attempt %d failed (%s);"
                        " retrying in %.1fs", identity, a + 1, exc, d))
            except OSError as exc:
                log.warning("elastic driver: notify %s failed after "
                            "retries: %s", identity, exc)

    # Assignment fields that define the world LAYOUT — everything except
    # the per-generation controller ports (freshly bind-probed each call,
    # so they always differ even when nothing else does).
    _LAYOUT_KEYS = ("rank", "size", "local_rank", "local_size",
                    "cross_rank", "cross_size", "hostname", "agent_port")

    def _same_layout(self, assignments: Dict[str, dict]) -> bool:
        def layout(table):
            return {i: tuple(a.get(k) for k in self._LAYOUT_KEYS)
                    for i, a in table.items()}
        return bool(self._assigned) and \
            layout(assignments) == layout(self._assigned)

    def _new_generation(self, hosts: List[DiscoveredHost]) -> bool:
        assignments = self.compute_assignments(hosts)
        if not assignments:
            return False
        if self._same_layout(assignments):
            # No-op regeneration guard (ISSUE 14): the active membership
            # and rank layout are IDENTICAL to the live generation — the
            # only delta would be freshly-allocated controller ports.
            # Re-publishing forces every healthy worker through a full
            # teardown/re-init for nothing, and the sub-second
            # back-to-back generations it produces are exactly what
            # strands a joining rank on a superseded init barrier (e.g.
            # a cordoned host aging past the discovery-grace window
            # right after its drain already re-formed the world).  Keep
            # the live generation; just respawn any exited identities
            # into it.
            for identity, a in self._assigned.items():
                proc = self._procs.get(identity)
                if proc is None or proc.poll() is not None:
                    self._spawn(identity, a)
            return True
        self._assigned = assignments
        if self._rdv_addr_explicit is None:
            from ..common.net import routable_addr
            self._rdv_addr = ("127.0.0.1"
                              if all(is_local_host(h.hostname) for h in hosts)
                              else routable_addr())
        version = self.rendezvous.publish(assignments)
        if self.verbose:
            log.warning("elastic driver: generation %s over %s", version,
                        sorted(assignments))
        # Terminate workers no longer assigned (removed/blacklisted hosts).
        for identity, proc in list(self._procs.items()):
            if identity not in assignments:
                self._released.add(identity)
                if proc.poll() is None:
                    proc.terminate()
        # Publish BEFORE notifying so a resetting worker always finds the
        # new generation waiting.
        self._notify_workers(version)
        for identity, a in assignments.items():
            proc = self._procs.get(identity)
            if (proc is not None and proc.poll() is None
                    and self.respawn_on_generation):
                # Replace the live worker: drop it from the table first so
                # its forced exit is never reaped as a host failure.
                del self._procs[identity]
                proc.terminate()
                proc = None
            if proc is None or proc.poll() is not None:
                self._spawn(identity, a)
        return True

    # ------------------------------------------------------------ main loop
    def run(self) -> int:
        deadline = time.monotonic() + self.start_timeout_s
        while True:
            try:
                discovered = self.discovery.find_available_hosts_and_slots()
            except Exception as exc:  # noqa: BLE001 - one bad poll must not
                # kill the driver (script timeout, malformed slots line, ...)
                log.warning("elastic driver: discovery failed: %s", exc)
                discovered = []
            # Effective = flap-debounced; blacklist/cordon applied at use.
            self._hosts = self._effective_hosts(discovered, time.monotonic())
            # Preemption notices gate the FIRST generation too: a host
            # with an active notice is cordoned (nothing is assigned yet,
            # so this is the cordon-only path) rather than knowingly
            # handed workers that would need an immediate drain.
            self._check_preemption()
            if self._new_generation(self.active_hosts(self._hosts)):
                break
            if time.monotonic() > deadline:
                log.warning("elastic driver: needed min_np=%s slots within "
                            "start timeout; giving up", self.min_np)
                self._shutdown_workers()
                return 1
            time.sleep(self.discovery_interval_s)

        last_poll = time.monotonic()
        last_autoscale = time.monotonic()
        while True:
            # 1. process exits
            changed = self._reap_exits()

            # 2. success: training completed on some rank; drain the rest
            if self._success.is_set():
                t_end = time.monotonic() + 30
                while self._procs and time.monotonic() < t_end:
                    for identity, proc in list(self._procs.items()):
                        if proc.poll() is not None:
                            del self._procs[identity]
                    time.sleep(0.1)
                self._shutdown_workers()
                return 0

            # 3. discovery poll (flap-debounced: a host must stay missing
            # past discovery_grace_s before it drops out of the world, so
            # one bad poll never churns rank assignments)
            if time.monotonic() - last_poll >= self.discovery_interval_s:
                last_poll = time.monotonic()
                try:
                    discovered = self.discovery.find_available_hosts_and_slots()
                    effective = self._effective_hosts(discovered,
                                                      time.monotonic())
                    if ([(h.hostname, h.slots) for h in effective]
                            != [(h.hostname, h.slots) for h in self._hosts]):
                        self._hosts = effective
                        changed = True
                except Exception as exc:  # noqa: BLE001 - transient poll
                    log.warning("elastic driver: discovery failed: %s", exc)
                # 3a. preemption notices (ISSUE 12): an imminently-
                # preempted host gets the proactive DRAIN → clean LEAVE →
                # cordon path — never a dead-peer verdict — handled on
                # every poll, with or without the autoscale policy
                # (hardware loss does not wait for an autoscale interval).
                self._check_preemption()

            # 3b. drain-grace enforcement: a drained worker that outlived
            # its deadline is terminated (the legacy sever fallback) —
            # still marked DRAINING, so the reap classifies it LEFT.
            self._enforce_drain_deadlines()

            # 3c. closed-loop autoscaling: consume monitor summaries, let
            # the policy decide, execute (docs/elastic.md).  Decisions
            # mutate the world only through the same discovery/cordon/
            # drain paths the rest of this loop already handles.
            if (self.autoscale_policy is not None
                    and time.monotonic() - last_autoscale
                    >= self.autoscale_interval_s):
                last_autoscale = time.monotonic()
                self._autoscale_step()

            # 4. re-form the world if needed.  The blacklist is re-applied
            # HERE so a failure-triggered regeneration excludes the host
            # that just failed, not only at discovery-poll boundaries.
            if changed:
                active = self.active_hosts(self._hosts)
                if not self._new_generation(active):
                    log.warning(
                        "elastic driver: %s slots < min_np=%s; aborting",
                        sum(h.slots for h in active), self.min_np)
                    self._shutdown_workers()
                    return self._first_failure_rc or 1

            time.sleep(0.05)

    def _reap_exits(self) -> bool:
        """Reap exited workers and classify each exit — the decision table
        the clean-exit tests pin (docs/elastic.md "Drain semantics"):

        - released (driver terminated it: host removed/shrunk) → LEFT;
        - draining (autoscale drain → clean LEAVE → exit) → LEFT: never
          the job-success signal, never a blacklisting failure — the host
          stays eligible for a later scale-out; triggers regeneration;
        - rc == 0 otherwise → SUCCESS (training completed somewhere);
        - rc != 0 → FAILURE: blacklist the host, trigger regeneration.

        Returns True when the world must re-form."""
        changed = False
        for identity, proc in list(self._procs.items()):
            rc = proc.poll()
            if rc is None:
                continue
            del self._procs[identity]
            self._close_out_files(identity)
            # A departed rank's shard server is gone with it: prune its
            # rendezvous state record so later peer restores don't burn
            # a connect timeout per corpse (ISSUE 14).
            self.rendezvous.drop_state(identity)
            if identity in self._released:
                self._released.discard(identity)
                self.registry.record_left(identity)
                continue
            if identity in self._draining:
                self._draining.discard(identity)
                self.registry.record_left(identity)
                if rc != 0:
                    log.warning("elastic driver: drained worker %s exited "
                                "rc=%s (expected 0)", identity, rc)
                changed = True
            elif rc == 0:
                self.registry.record_success(identity)
                if identity in self._assigned:
                    self._success.set()
            else:
                self.registry.record_failure(identity)
                if self.verbose:
                    log.warning("elastic driver: %s failed rc=%s",
                                identity, rc)
                if not self._success.is_set():
                    self._first_failure_rc = self._first_failure_rc or rc
                    changed = True
        return changed

    # ------------------------------------------------------- autoscaling
    def _default_autoscale_source(self):
        """Poll rank 0's monitor ``/health`` (which carries the
        ``RankAggregator.summary()`` fields — spread, trends, queue depth,
        cycle counters) for the policy's observation record.  Needs
        ``HOROVOD_MONITOR_PORT`` forwarded to the workers; returns None —
        a hold — when the exporter is not up (e.g. mid-re-rendezvous)."""
        import json
        import urllib.request
        port = int(self.extra_env.get("HOROVOD_MONITOR_PORT", "0") or 0)
        if port <= 0 or not self._assigned:
            return None
        a = next((a for a in self._assigned.values() if a["rank"] == 0),
                 None)
        if a is None:
            return None
        host = a["controller_addr"]
        with urllib.request.urlopen(f"http://{host}:{port}/health",
                                    timeout=2.0) as r:
            return json.loads(r.read().decode())

    def drain_worker(self, identity: str) -> bool:
        """Ask one worker to drain: finish its batch, send the clean
        LEAVE, exit 0 (``DRAIN`` verb on the notification channel —
        the worker-side handler raises ``DrainRequested`` from the next
        ``state.commit()``).  The identity's exit is then classified as a
        departure, never a failure.  Best-effort: False when the worker
        has no registered notification port or the ping failed."""
        if identity in self._draining:
            return True
        port = self.rendezvous.notification_ports().get(identity)
        if port is None:
            log.warning("elastic driver: cannot drain %s (no notification "
                        "port registered)", identity)
            return False
        host = identity.rsplit(":", 1)[0]
        addr = "127.0.0.1" if is_local_host(host) else host
        try:
            with socket.create_connection((addr, port), timeout=2.0) as s:
                s.sendall(b"DRAIN\n")
        except OSError as exc:
            log.warning("elastic driver: drain ping to %s failed: %s",
                        identity, exc)
            return False
        self._draining.add(identity)
        return True

    def cordon(self, hostname: str) -> None:
        """Retire a host from assignment (clean — unlike the blacklist,
        the record carries no failure; discovery dropping the host, or an
        operator re-adding capacity elsewhere, is the durable state)."""
        self._cordoned.add(hostname)

    # ------------------------------------------------- preemption drains
    def _request_commit_all(self, wait_s: float = 2.0) -> Dict[str, bool]:
        """Checkpoint pacing (ISSUE 12): ask every live worker to commit
        its elastic state NOW — sent immediately before an imminent
        scale/preemption decision executes, so the last commit predates
        the world change by milliseconds instead of a timer period.
        Best-effort, and fanned out in PARALLEL with a bounded wait: on
        the preemption path every second counts against the grace
        window, so one unreachable worker must not serialize the rest.
        The workers' own commit cadence is the backstop.

        ISSUE 14 bugfix: workers now ACK the ping, the per-worker acks
        are recorded in the event log (``action: commit_request``), and
        the dict is returned so the preempt drain can WAIT (grace-
        bounded) for the doomed host's ack before cordoning — previously
        nothing recorded whether any worker ever saw the request, and a
        drain could race its own in-flight snapshot ping."""
        acks: Dict[str, bool] = {}

        def _ping(identity, addr, port):
            try:
                with socket.create_connection((addr, port),
                                              timeout=1.0) as s:
                    s.sendall(b"COMMIT\n")
                    s.settimeout(max(0.5, wait_s))
                    # Read to the newline (bounded): a single recv can
                    # legally return a partial segment of "ACK\n", and a
                    # false-negative ack here cordons a host early on the
                    # exact path built to make acks truthful.
                    buf = b""
                    while b"\n" not in buf and len(buf) < 64:
                        c = s.recv(8)
                        if not c:
                            break
                        buf += c
                    if buf.startswith(b"ACK"):
                        acks[identity] = True
            except OSError:
                pass

        pings = []
        for identity, port in self.rendezvous.notification_ports().items():
            if identity not in self._procs:
                continue
            acks[identity] = False
            host = identity.rsplit(":", 1)[0]
            addr = "127.0.0.1" if is_local_host(host) else host
            t = threading.Thread(target=_ping, args=(identity, addr, port),
                                 daemon=True)
            t.start()
            pings.append(t)
        deadline = time.monotonic() + max(0.5, wait_s)
        for t in pings:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        self.events.append({"action": "commit_request",
                            "acks": dict(acks),
                            "acked": sorted(i for i, ok in acks.items()
                                            if ok),
                            "ts": time.time()})
        return acks

    def _check_preemption(self) -> None:
        """Consume the discovery source's preemption notices.  A noticed
        ASSIGNED host is drained proactively — commit request → cordon →
        DRAIN pings with a ``preempt_grace_s`` deadline — so the
        departure takes the clean-LEAVE path before the hardware
        disappears.  A noticed host OUTSIDE the current assignment is
        cordoned too (a scale-out must never place workers on doomed
        hardware).  Preemption cordons are RELEASED when their notice
        clears: recreated preemptible hardware under the same address —
        the normal TPU preemption lifecycle — rejoins the world, and a
        later notice re-triggers the drain."""
        try:
            notices = set(self.discovery.preemption_notices())
        except Exception as exc:  # noqa: BLE001 - transient, like discovery
            log.warning("elastic driver: preemption poll failed: %s", exc)
            return
        for host in sorted(self._preempt_cordoned - notices):
            self._preempt_cordoned.discard(host)
            self._cordoned.discard(host)
            log.warning("elastic driver: preemption notice for %s "
                        "cleared; host un-cordoned", host)
        assigned_hosts = {a["hostname"] for a in self._assigned.values()}
        for host in sorted(notices):
            if host in self._cordoned:
                continue           # already handled (or evict-cordoned)
            self._preempt_cordoned.add(host)
            if host in assigned_hosts:
                self._preempt_drain(host)
            else:
                # Not in this world (yet): cordon only, so the doomed
                # host can't be assigned while the notice stands.
                self.cordon(host)
                log.warning("elastic driver: preemption notice for "
                            "unassigned host %s; cordoned", host)

    def _preempt_drain(self, host: str) -> None:
        """Execute one preemption drain.  The policy (when attached) is
        the decision source of record — a notice outranks its
        queue/straggler signals and opens its cooldown window — but the
        drain itself never waits on autoscaling being enabled.  min_np is
        deliberately NOT a guard here: the hardware is going away either
        way, and an orderly departure that later under-runs min_np still
        beats a mid-collective crash with a dead-peer verdict."""
        reason = f"preemption notice for host {host} (discovery)"
        if self.autoscale_policy is not None:
            try:
                decision = self.autoscale_policy.observe(
                    {}, size=len(self._assigned), preempt_hosts=(host,))
                if getattr(decision, "action", "") == "preempt":
                    reason = decision.reason
            except Exception:  # noqa: BLE001 - policy bookkeeping is
                pass           # advisory; the drain happens regardless
        log.warning("elastic driver: PREEMPT drain of host %s (%s)",
                    host, reason)
        self.events.append({"action": "preempt_drain", "host": host,
                            "reason": reason, "ts": time.time()})
        # Commit first (checkpoint pacing), then cordon so the clean exit
        # regenerates a world that excludes the host, then drain.  The
        # commit fan-out WAITS — bounded to a slice of the grace window —
        # for the workers' acks before the cordon (ISSUE 14 bugfix): a
        # drain must not race an in-flight snapshot request, and a
        # missing ack is logged so the operator can see WHO never got the
        # pacing ping (its restore point is one timer period older).
        wait_s = (min(5.0, max(1.0, self.preempt_grace_s / 4.0))
                  if self.preempt_grace_s > 0 else 1.0)
        acks = self._request_commit_all(wait_s=wait_s)
        missing = sorted(i for i, ok in acks.items() if not ok)
        if missing:
            log.warning(
                "elastic driver: preempt drain of %s proceeding without "
                "commit acks from %s (waited %.1fs); their restore point "
                "is their last periodic commit", host, missing, wait_s)
        self.cordon(host)
        deadline = time.monotonic() + self.preempt_grace_s
        for identity, a in list(self._assigned.items()):
            if a["hostname"] != host:
                continue
            if self.drain_worker(identity):
                self._drain_deadlines[identity] = deadline
            else:
                # Unreachable worker: the termination fallback, marked
                # DRAINING so the reap still classifies it LEFT and
                # triggers the regeneration.
                proc = self._procs.get(identity)
                if proc is not None and proc.poll() is None:
                    self._draining.add(identity)
                    proc.terminate()

    def _enforce_drain_deadlines(self) -> None:
        """The grace fallback: a drained worker still alive past its
        deadline is terminated — the legacy sever path — but stays
        classified as a departure (DRAINING → LEFT), never a blacklist."""
        if not self._drain_deadlines:
            return
        now = time.monotonic()
        for identity, deadline in list(self._drain_deadlines.items()):
            proc = self._procs.get(identity)
            if proc is None or proc.poll() is not None:
                self._drain_deadlines.pop(identity, None)
                continue
            if now >= deadline:
                self._drain_deadlines.pop(identity, None)
                log.warning(
                    "elastic driver: drain grace (%.0fs) expired for %s; "
                    "falling back to termination", self.preempt_grace_s,
                    identity)
                proc.terminate()

    def _run_scale_command(self, action: str, decision,
                           host: Optional[str] = None) -> None:
        """Invoke the operator's capacity hook (``--scale-command``): a
        shell command receiving the decision through HVD_AUTOSCALE_*
        env — the cloud-agnostic seam where a deployment resizes its
        instance group / TPU slice pool.  Discovery is still the source
        of truth: the command changes what the discovery script reports,
        the driver reacts as it would to any host change."""
        if not self.scale_command:
            return
        env = dict(os.environ)
        env["HVD_AUTOSCALE_ACTION"] = action
        if decision.target_size is not None:
            env["HVD_AUTOSCALE_TARGET"] = str(decision.target_size)
        if host is not None:
            env["HVD_AUTOSCALE_HOST"] = host
        try:
            out = subprocess.run(self.scale_command, shell=True, env=env,
                                 capture_output=True, text=True, timeout=60)
            if out.returncode != 0:
                log.warning("elastic driver: scale command rc=%s: %s",
                            out.returncode, (out.stderr or "").strip())
        except Exception as exc:  # noqa: BLE001 - capacity hook is
            # best-effort; the policy retries after its cooldown
            log.warning("elastic driver: scale command failed: %s", exc)

    def _autoscale_step(self) -> None:
        """One observe→decide→execute turn of the autoscaler."""
        try:
            src = self._autoscale_source or self._default_autoscale_source
            summary = src()
        except Exception as exc:  # noqa: BLE001 - telemetry outage = hold
            log.info("elastic driver: autoscale source unavailable: %s",
                     exc)
            return
        if not summary:
            return
        decision = self.autoscale_policy.observe(summary,
                                                 size=len(self._assigned))
        if decision.is_hold:
            return
        # Checkpoint pacing (ISSUE 12): a non-hold decision is about to
        # change the world — ask every worker to commit NOW, not at its
        # next timer tick, so the restore point predates the change.
        self._request_commit_all()
        event = {"action": decision.action, "reason": decision.reason,
                 "target_size": decision.target_size,
                 "evict_rank": decision.evict_rank, "ts": time.time()}
        if decision.action == "evict":
            identity = next(
                (i for i, a in self._assigned.items()
                 if a["rank"] == decision.evict_rank), None)
            if identity is None or identity in self._draining:
                return
            host = self._assigned[identity]["hostname"]
            if not self._host_removable(host):
                log.warning(
                    "elastic driver: autoscale EVICT of %s skipped — "
                    "retiring host %s would drop below min_np=%s",
                    identity, host, self.min_np)
                return
            event["identity"], event["host"] = identity, host
            log.warning("elastic driver: autoscale EVICT %s (%s)",
                        identity, decision.reason)
            # Cordon first, then drain: when the worker's clean exit
            # triggers the regeneration, the host is already excluded.
            self.cordon(host)
            if not self.drain_worker(identity):
                # Unreachable worker: fall back to termination.  Marked
                # DRAINING (not released) so the reap classifies it as a
                # departure AND triggers the regeneration — a released
                # exit is silently skipped, which would leave the
                # survivors waiting on a generation that never forms.
                proc = self._procs.get(identity)
                if proc is not None and proc.poll() is None:
                    self._draining.add(identity)
                    proc.terminate()
            self._run_scale_command("evict", decision, host=host)
        elif decision.action == "scale_out":
            log.warning("elastic driver: autoscale SCALE_OUT -> %s (%s)",
                        decision.target_size, decision.reason)
            self._run_scale_command("scale_out", decision)
        elif decision.action == "scale_in":
            # Retire the LAST host of the current generation that does
            # not carry the coordinator (host 0 must survive a shrink).
            order: List[str] = []
            for a in sorted(self._assigned.values(),
                            key=lambda a: a["rank"]):
                if a["hostname"] not in order:
                    order.append(a["hostname"])
            victims = [h for h in order[1:] if self._host_removable(h)]
            if not victims:
                return
            host = victims[-1]
            event["host"] = host
            log.warning("elastic driver: autoscale SCALE_IN: draining "
                        "host %s (%s)", host, decision.reason)
            self.cordon(host)
            for identity, a in self._assigned.items():
                if a["hostname"] == host:
                    self.drain_worker(identity)
            self._run_scale_command("scale_in", decision, host=host)
        self.events.append(event)

    def _host_removable(self, host: str) -> bool:
        """min_np at HOST granularity: the policy approves scale-in/evict
        from rank counts, but retiring a host removes ALL its slots —
        on multi-slot hosts that can undershoot min_np and the driver
        would abort the whole job at the next regeneration.  A host is
        removable only if the surviving assignment still covers min_np."""
        remaining = sum(1 for a in self._assigned.values()
                        if a["hostname"] != host)
        return remaining >= self.min_np

    def _close_out_files(self, identity: str):
        for fh in self._out_files.pop(identity, ()):
            try:
                fh.close()
            except OSError:  # pragma: no cover
                pass

    def _shutdown_workers(self):
        # Snapshot: tests (and operators) may call this from another
        # thread while the run loop's reap is still mutating the table.
        procs = list(self._procs.values())
        for proc in procs:
            if proc.poll() is None:
                proc.terminate()
        t_end = time.monotonic() + 10
        for proc in procs:
            while proc.poll() is None and time.monotonic() < t_end:
                time.sleep(0.05)
            if proc.poll() is None:
                proc.kill()
        self._procs.clear()
        for identity in list(self._out_files):
            self._close_out_files(identity)
        self.rendezvous.stop()


def run_elastic(args) -> int:
    """``torovodrun --host-discovery-script`` entry (reference:
    ``_run_elastic``)."""
    min_np = args.min_np or args.np or 1
    max_np = args.max_np
    if getattr(args, "tpu_metadata_discovery", False):
        from .discovery import TPUMetadataDiscovery
        discovery = TPUMetadataDiscovery(
            slots_per_host=args.slots_per_host or 0)
    else:
        discovery = HostDiscoveryScript(args.host_discovery_script,
                                        default_slots=args.slots_per_host
                                        or 1)
    # One knob table for every launch path: tuning_env covers the fusion/
    # cycle/cache/pipeline/stall/monitor/autotune flags, so a knob can
    # never work on the static path and silently vanish on the elastic
    # one (this loop used to be a drifting hand copy).  A join epoch also
    # flushes each worker's monitor aggregation table — that hook lives in
    # the controller client, so re-ranked survivors start clean.
    from ..runner.run import tuning_env
    extra_env = tuning_env(args)
    # Trace/timeline filenames travel as the BASE: ranks are assigned at
    # rendezvous, so elastic workers apply the shared per-rank suffix
    # (utils.timeline.per_rank_filename) in elastic_bootstrap — the same
    # <base>.<rank> names every other launch path produces.
    if getattr(args, "timeline_filename", None):
        extra_env["HOROVOD_TIMELINE"] = args.timeline_filename
    if getattr(args, "trace_filename", None):
        extra_env["HOROVOD_TRACE"] = args.trace_filename
    # Closed-loop autoscaling (docs/elastic.md): the policy lives in the
    # DRIVER process, parameterized from the same HOROVOD_AUTOSCALE_*
    # env table Config documents (the launcher's env, not the workers').
    from ..common.config import Config
    cfg = Config.from_env()
    autoscale_on = cfg.autoscale or getattr(args, "autoscale", False)
    policy = None
    if autoscale_on:
        from .autoscale import ScalePolicy
        policy = ScalePolicy(
            min_np=min_np, max_np=max_np,
            queue_high=cfg.autoscale_queue_high,
            queue_trend_up=cfg.autoscale_queue_trend,
            straggler_factor=cfg.autoscale_straggler_factor,
            persistence=cfg.autoscale_persistence,
            cooldown_s=cfg.autoscale_cooldown_s,
            idle_s=cfg.autoscale_idle_s,
            commit_max_age_s=cfg.commit_max_age_s,
            rate_high=cfg.autoscale_rate_high,
            latency_target_ms=cfg.autoscale_latency_target_ms,
            idle_qps=cfg.autoscale_idle_qps)
        if not extra_env.get("HOROVOD_MONITOR_PORT"):
            log.warning(
                "autoscale enabled without --monitor-port: the driver has "
                "no monitor endpoint to observe, so the policy will hold "
                "forever; pass --monitor-port to close the loop")
    driver = ElasticDriver(
        discovery, args.command, min_np=min_np, max_np=max_np,
        env=extra_env, start_timeout_s=args.start_timeout,
        output_filename=args.output_filename, verbose=args.verbose,
        autoscale_policy=policy,
        autoscale_interval_s=(getattr(args, "autoscale_interval", None)
                              or cfg.autoscale_interval_s),
        scale_command=getattr(args, "scale_command", None),
        # `is not None`, not `or`: an explicit --preempt-grace-s 0
        # (terminate immediately) is a valid setting, not an unset one.
        preempt_grace_s=(getattr(args, "preempt_grace_s", None)
                         if getattr(args, "preempt_grace_s", None)
                         is not None else cfg.preempt_grace_s))
    try:
        return driver.run()
    finally:
        try:
            driver.rendezvous.stop()
        except Exception:  # noqa: BLE001 - already stopped
            pass
