"""Elastic training: state commit/restore/sync and the run wrapper.

Parity with the reference's framework-agnostic elastic layer
(``horovod/common/elastic.py`` — SURVEY.md §2b P1, §3.4): a ``State`` object
with ``commit`` (in-memory backup), ``restore`` (rollback after a peer
failure) and ``sync`` (rank-0 broadcast so joiners catch up), plus the
``@hvd.elastic.run`` decorator that catches ``HorovodInternalError`` /
``HostsUpdatedInterrupt``, re-initializes the runtime, and retries.

TPU mapping (SURVEY.md §5 "failure detection"): a lost host invalidates the
ICI mesh, so recovery re-runs ``init()`` (rebuilding mesh + engine, which
also invalidates compiled-program caches) before ``state.sync()``.

Import shape: the jax-free halves (driver, discovery, registration,
rendezvous, the ``autoscale`` policy engine, the control-flow exceptions)
stay importable without jax so the fast test tier, the launcher process and
the synthetic-load acceptance workers can use them; the state objects
(``State``/``ObjectState``/``JaxState``/``run``) hold device arrays and
load lazily on first attribute access (PEP 562)."""

from ..common.exceptions import (  # noqa: F401  (jax-free re-exports)
    DrainRequested, HorovodInternalError, HostsUpdatedInterrupt,
    PeerLeftInterrupt,
)
from .discovery import (  # noqa: F401
    DiscoveredHost, FixedHostDiscovery, HostDiscovery, HostDiscoveryScript,
)
from .registration import WorkerStateRegistry  # noqa: F401

# Lazily-loaded jax-backed state layer (elastic/state.py imports jax).
_STATE_ATTRS = ("State", "ObjectState", "JaxState", "run")


def __getattr__(name):
    if name in _STATE_ATTRS:
        from . import state as _state
        return getattr(_state, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(_STATE_ATTRS))
