"""Elastic training: state commit/restore/sync and the run wrapper.

Parity with the reference's framework-agnostic elastic layer
(``horovod/common/elastic.py`` — SURVEY.md §2b P1, §3.4): a ``State`` object
with ``commit`` (in-memory backup), ``restore`` (rollback after a peer
failure) and ``sync`` (rank-0 broadcast so joiners catch up), plus the
``@hvd.elastic.run`` decorator that catches ``HorovodInternalError`` /
``HostsUpdatedInterrupt``, re-initializes the runtime, and retries.

TPU mapping (SURVEY.md §5 "failure detection"): a lost host invalidates the
ICI mesh, so recovery re-runs ``init()`` (rebuilding mesh + engine, which
also invalidates compiled-program caches) before ``state.sync()``.
"""

from .state import (  # noqa: F401
    State, ObjectState, JaxState,
    HorovodInternalError, HostsUpdatedInterrupt, run,
)
from .discovery import (  # noqa: F401
    DiscoveredHost, FixedHostDiscovery, HostDiscovery, HostDiscoveryScript,
)
from .registration import WorkerStateRegistry  # noqa: F401
