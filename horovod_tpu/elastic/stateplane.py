"""Resilient state plane: overlap-scheduled sharded checkpoints +
peer-to-peer elastic restore (no jax imports).

The ROADMAP's sharded-state item, robustness half: durability and
recovery stop stealing step time by treating checkpoint I/O as just
another scheduled tensor stream, and by restoring re-joiners from the
survivors' memory instead of from disk.

**Overlap-scheduled sharded checkpoints.**  On ``state.commit()`` (paced
by the driver's COMMIT pings — ``state.should_commit()``) each rank
serializes the committed state once and takes its **1/N shard** of the
byte stream — the same pad-to-multiple + even-slice math
``parallel/zero.py`` uses for optimizer-state shards, applied to the
serialized blob — so fleet-wide checkpoint bytes are written once, not N
times.  The durable write is CHUNKED and streamed through the engine's
priority dispatch backlog (PR 7) in a new lowest-priority ``checkpoint``
lane (:data:`~..ops.scheduler.CKPT_LANE`): gradient batches always
dispatch first, the fused-lane budget never counts a checkpoint chunk
(the pure-function budget rule is unchanged), and a bounded number of
chunks ride each cycle's tail.  Durability is two-phase per artifact —
write ``<file>.tmp`` → flush+fsync → atomic rename — and the per-rank
shard manifest is renamed LAST, so a torn or partial checkpoint is never
observable: an epoch exists exactly when every rank's manifest does.
Chunk writes retry with backoff (:func:`~..common.net.retry_with_backoff`)
and a persistent write failure abandons the epoch with attribution — the
previous durable epoch remains the restore point.

**Peer-to-peer elastic restore.**  Every committed epoch is also held in
memory and served by a tiny per-rank :class:`ShardServer`.  On
re-rendezvous a joining rank declares its state epoch in the rendezvous
metadata (``elastic/rendezvous.py`` state records) and, when survivors
hold a NEWER epoch, restores by fetching 1/K shards from the K reachable
survivors (each holds the full committed blob, so any survivor can serve
any shard — a dead peer mid-restore just moves its shard to the next
one) and verifying the reassembled blob against the survivors' digest —
**zero disk reads**.  Disk (the manifest, newest complete epoch wins;
corrupt shards quarantined with rank attribution) is the fallback when
no quorum of newer-epoch survivors exists.

Fault points (``HVD_TPU_FAULT`` — :mod:`horovod_tpu.testing.faults`):
``ckpt_write_fail`` (each shard-chunk write attempt), ``ckpt_torn``
(between the shard rename and the manifest rename — a crash here leaves
a torn epoch that restore must skip), ``restore_peer_exit`` (a survivor
about to serve a shard — ``econnreset``/``crash`` model a peer dying
mid-restore).

**Trust model** (same as the rest of the control plane): the rendezvous
KV, the shard servers and the coordinator sockets are unauthenticated,
and restored state decodes through pickle for non-array values —
exactly like the existing ``broadcast_object``/``state.sync()`` wire.
Everything here assumes the fleet-private network the launcher runs on;
never expose the rendezvous or shard ports beyond it.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import pickle
import re
import socket
import struct
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..common.net import retry_with_backoff
from ..utils.logging import get_logger

log = get_logger()

_EPOCH_RE = re.compile(r"^epoch_(\d+)$")
_SHARD_RE = re.compile(r"^shard_(\d+)_of_(\d+)\.json$")

# Serialized-blob framing: numpy arrays go through np.save (portable,
# version-stable), everything else through pickle — one length-prefixed
# record per state key.
_MAGIC = b"HVSP1\n"


# ------------------------------------------------------------- shard math
def shard_bounds(total: int, world: int) -> Tuple[int, int]:
    """``(per, pad)`` for an even 1/world byte split: the blob is padded
    to a multiple of ``world`` and sliced evenly — the byte-stream
    analogue of ``parallel/zero.py``'s ``_shard_leaf`` pad-to-multiple +
    ``psum_scatter`` slice convention, so every rank derives identical
    shard boundaries from (total, world) alone."""
    world = max(1, int(world))
    pad = (-total) % world
    return (total + pad) // world, pad


def shard_of(blob: bytes, index: int, world: int) -> bytes:
    """Shard ``index`` of ``world`` (zero-padded tail, like zero.py's
    padded last shard)."""
    per, pad = shard_bounds(len(blob), world)
    start = index * per
    piece = blob[start:start + per]
    if len(piece) < per:
        piece = piece + b"\x00" * (per - len(piece))
    return piece


def shard_slice_array(arr, rank: int, world: int):
    """Rank's 1/world slice of a flattened numpy array under the SAME
    pad+slice convention as the byte shards above (and zero.py's leaf
    shards): pad with zeros to a multiple of ``world``, slice evenly.
    jax-free — the churn harness asserts a re-joiner's recovered
    optimizer slice with it, and it is pinned equal to
    ``parallel/zero.py``'s device-side slicing by the unit tier."""
    import numpy as np
    flat = np.asarray(arr).reshape(-1)
    per, pad = shard_bounds(flat.size, world)
    if pad:
        flat = np.concatenate([flat, np.zeros((pad,), flat.dtype)])
    r = max(0, int(rank))
    return flat[r * per:(r + 1) * per]


def blob_digest(blob: bytes) -> str:
    return hashlib.blake2b(blob, digest_size=16).hexdigest()


# ----------------------------------------------------------- serialization
def encode_state(state: Dict) -> bytes:
    """Serialize a committed state dict (numpy arrays + picklable
    scalars/objects) to one deterministic byte blob."""
    import numpy as np
    out = io.BytesIO()
    out.write(_MAGIC)
    for k in sorted(state):
        v = state[k]
        if isinstance(v, np.ndarray):
            kind = b"N"
            buf = io.BytesIO()
            np.save(buf, v, allow_pickle=False)
            payload = buf.getvalue()
        else:
            kind = b"P"
            payload = pickle.dumps(v, protocol=4)
        key = k.encode()
        out.write(struct.pack("<I", len(key)) + key)
        out.write(kind + struct.pack("<Q", len(payload)))
        out.write(payload)
    return out.getvalue()


def decode_state(blob: bytes) -> Dict:
    import numpy as np
    src = io.BytesIO(blob)
    if src.read(len(_MAGIC)) != _MAGIC:
        raise ValueError("state plane: bad blob magic (corrupt or foreign)")
    out: Dict = {}
    while True:
        head = src.read(4)
        if not head:
            return out
        (klen,) = struct.unpack("<I", head)
        key = src.read(klen).decode()
        kind = src.read(1)
        (plen,) = struct.unpack("<Q", src.read(8))
        payload = src.read(plen)
        if kind == b"N":
            out[key] = np.load(io.BytesIO(payload), allow_pickle=False)
        else:
            out[key] = pickle.loads(payload)


# --------------------------------------------------------------- manifests
def _epoch_dir(directory: str, epoch: int) -> str:
    return os.path.join(directory, f"epoch_{epoch:010d}")


def _shard_base(rank: int, world: int) -> str:
    return f"shard_{rank}_of_{world}"


def _fsync_write(path: str, data: bytes) -> None:
    """Two-phase file write: ``path.tmp`` → flush + fsync → rename."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as fh:
        fh.write(data)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


def list_epochs(directory: str) -> List[int]:
    if not os.path.isdir(directory):
        return []
    return sorted(int(m.group(1)) for d in os.listdir(directory)
                  if (m := _EPOCH_RE.match(d)))


def epoch_manifests(directory: str, epoch: int) -> Optional[List[dict]]:
    """The epoch's per-rank manifests when the epoch is COMPLETE (every
    rank's manifest present, parseable, mutually consistent), else None.
    A torn manifest — the ``.tmp`` that a crash between the shard rename
    and the manifest rename leaves behind, or an unparseable file — makes
    the epoch incomplete: it is skipped, never loaded."""
    d = _epoch_dir(directory, epoch)
    if not os.path.isdir(d):
        return None
    manifests: Dict[int, dict] = {}
    world = None
    for name in os.listdir(d):
        m = _SHARD_RE.match(name)
        if not m:
            continue
        try:
            with open(os.path.join(d, name)) as fh:
                rec = json.load(fh)
        except (OSError, ValueError):
            return None                      # torn manifest: epoch unusable
        r, w = int(m.group(1)), int(m.group(2))
        if rec.get("rank") != r or rec.get("world") != w:
            return None
        if world is None:
            world = w
        elif world != w:
            return None                      # mixed-world write: unusable
        manifests[r] = rec
    if world is None or set(manifests) != set(range(world)):
        return None
    return [manifests[r] for r in range(world)]


def latest_complete_epoch(directory: str) -> Optional[int]:
    """Newest epoch whose every shard manifest is present and valid —
    'newest complete epoch wins'."""
    for epoch in reversed(list_epochs(directory)):
        if epoch_manifests(directory, epoch) is not None:
            return epoch
    return None


# --------------------------------------------------------------- write job
class _WriteJob:
    """One epoch's durable write: this rank's shard, chunked, two-phase.

    Chunks run on the engine's checkpoint lane (or inline when no engine
    is attached); the LAST chunk finalizes — shard fsync+rename, then the
    manifest fsync+rename (the commit point).  Superseding commits cancel
    unfinished jobs (newest epoch wins; fast commit cadence must not pile
    up a backlog of doomed epochs)."""

    def __init__(self, plane: "StatePlane", epoch: int, blob: bytes):
        self.plane = plane
        self.epoch = epoch
        # Snapshot the rank/world/generation the job was cut for: an
        # elastic re-bind (obtain() renumbering the plane mid-job) must
        # not make _finalize write a manifest whose rank/world disagree
        # with the shard filename — epoch_manifests would reject it and
        # the epoch would stay incomplete forever.
        self.rank = plane.rank
        self.world = plane.world
        self.generation = plane.generation
        self.shard = shard_of(blob, self.rank, self.world)
        self.total = len(blob)
        self.blob_digest = blob_digest(blob)
        self.shard_digest = blob_digest(self.shard)
        self.canceled = False
        self.failed: Optional[BaseException] = None
        self.done = False
        self._fh = None
        base = _shard_base(self.rank, self.world)
        self._dir = _epoch_dir(plane.directory, epoch)
        self._bin = os.path.join(self._dir, base + ".bin")
        self._man = os.path.join(self._dir, base + ".json")

    def chunk_items(self, chunk_bytes: int) -> List:
        from ..ops.scheduler import CheckpointChunk
        n = len(self.shard)
        chunk_bytes = max(1, int(chunk_bytes))
        offs = list(range(0, n, chunk_bytes)) or [0]
        items = []
        for i, off in enumerate(offs):
            final = i == len(offs) - 1
            items.append(CheckpointChunk(
                name=f"ckpt.e{self.epoch}.r{self.rank}"
                     f".c{i}/{len(offs)}",
                run=(lambda off=off, final=final:
                     self._run_chunk(off, chunk_bytes, final)),
                fail=self.abort))
        return items

    # The chunk body is deliberately small: one bounded write per lane
    # dispatch, so a cycle's checkpoint tail costs microseconds and the
    # stream overlaps training instead of stalling a cycle.
    def _run_chunk(self, off: int, size: int, final: bool) -> None:
        if self.canceled or self.failed is not None:
            self._cleanup()
            return
        try:
            retry_with_backoff(
                lambda: self._write(off, size),
                retries=self.plane.io_retries,
                base_ms=self.plane.io_backoff_ms, max_ms=2000.0)
            self.plane.chunks_written += 1
            if final:
                self._finalize()
        except OSError as exc:
            self.failed = exc
            self._cleanup()
            self.plane._job_failed(self, exc)

    def _write(self, off: int, size: int) -> None:
        fire = self.plane._fire
        if fire is not None:
            fire("ckpt_write_fail", self.rank)
        if self._fh is None:
            os.makedirs(self._dir, exist_ok=True)
            self._fh = open(self._bin + ".tmp", "wb")
        self._fh.seek(off)
        self._fh.write(self.shard[off:off + size])

    def _finalize(self) -> None:
        fh, self._fh = self._fh, None
        if fh is not None:
            fh.flush()
            os.fsync(fh.fileno())
            fh.close()
        os.replace(self._bin + ".tmp", self._bin)
        # The torn-checkpoint window: the shard landed but the manifest —
        # the commit point — has not.  A crash here leaves a .tmp (or
        # nothing), so the epoch stays incomplete and restore skips it.
        fire = self.plane._fire
        if fire is not None:
            fire("ckpt_torn", self.rank)
        _fsync_write(self._man, json.dumps({
            "epoch": self.epoch, "generation": self.generation,
            "rank": self.rank, "world": self.world,
            "nbytes": len(self.shard), "total": self.total,
            "digest": self.shard_digest, "blob_digest": self.blob_digest,
            "ts": round(time.time(), 3),
        }).encode())
        self.done = True
        self.plane._job_durable(self)

    def cancel(self) -> None:
        self.canceled = True

    def abort(self, exc: BaseException) -> None:
        """Engine-abort path (the lane is draining on a fault): the epoch
        is abandoned, the previous durable epoch remains."""
        if self.done or self.failed is not None:
            return
        self.failed = exc
        self._cleanup()
        self.plane._job_failed(self, exc)

    def _cleanup(self) -> None:
        fh, self._fh = self._fh, None
        if fh is not None:
            try:
                fh.close()
            except OSError:
                pass
        for path in (self._bin + ".tmp", self._man + ".tmp"):
            try:
                os.unlink(path)
            except OSError:
                pass


# -------------------------------------------------------------- shard serve
class ShardServer:
    """Tiny per-rank TCP service for peer-to-peer restore.

    One request per connection, newline-framed header, binary payload::

        EPOCH\\n                      -> EPOCH <epoch> <total> <digest>\\n
        SHARD <epoch> <i> <k>\\n      -> OK <nbytes> <digest>\\n<payload>
                                         (shard i of a k-way split of the
                                         in-memory blob) or ERR <why>\\n

    The split factor ``k`` is the REQUESTER's choice: every serving rank
    holds the full committed blob, so a joiner fetches 1/K from each of
    its K reachable survivors (and re-fetches a dead peer's shard from
    any other — the quorum is "at least one reachable newer-epoch
    survivor", because any one can serve everything)."""

    def __init__(self, plane: "StatePlane", addr: str = "0.0.0.0"):
        self.plane = plane
        self._sock = socket.socket()
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((addr, 0))
        self._sock.listen(16)
        self.served = 0
        self._thread = threading.Thread(target=self._serve, daemon=True,
                                        name="hvd-tpu-shard-server")
        self._thread.start()

    @property
    def port(self) -> int:
        return self._sock.getsockname()[1]

    def _serve(self) -> None:
        while True:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            try:
                conn.settimeout(5.0)
                self._handle(conn)
            except OSError:
                pass
            finally:
                try:
                    conn.close()
                except OSError:
                    pass

    def _handle(self, conn: socket.socket) -> None:
        line = conn.makefile("rb").readline().decode().strip()
        epoch, blob, digest = self.plane.memory_state()
        if line == "EPOCH":
            total = len(blob) if blob is not None else 0
            conn.sendall(f"EPOCH {epoch} {total} {digest or '-'}\n".encode())
            return
        parts = line.split()
        if len(parts) != 4 or parts[0] != "SHARD":
            conn.sendall(b"ERR bad request\n")
            return
        want_epoch, index, count = (int(parts[1]), int(parts[2]),
                                    int(parts[3]))
        # The plane retains the PREVIOUS committed epoch beside the
        # current one: a survivor committing mid-way through a joiner's
        # multi-shard fetch must keep serving the epoch the fetch
        # started on, or every donor would go "stale" at once and the
        # peer path would silently degrade to disk under active
        # training.
        blob = self.plane.blob_for(want_epoch)
        if blob is None:
            conn.sendall(f"ERR stale epoch (have {epoch})\n".encode())
            return
        piece = shard_of(blob, index, count)
        conn.sendall(f"OK {len(piece)} {blob_digest(piece)}\n".encode())
        # The peer-death-mid-restore fault point: the header is out, the
        # payload is not — exactly the torn-transfer shape a crashing
        # survivor produces.  econnreset severs this connection; crash
        # kills the whole serving process.
        fire = self.plane._fire
        if fire is not None:
            fire("restore_peer_exit", self.plane.rank,
                 sever=lambda: conn.shutdown(socket.SHUT_RDWR))
        conn.sendall(piece)
        self.served += 1

    def stop(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


# ------------------------------------------------------------ peer clients
def _ask(addr: str, port: int, request: str,
         timeout: float = 3.0) -> Tuple[str, socket.socket]:
    s = socket.create_connection((addr, port), timeout=timeout)
    s.settimeout(timeout)
    s.sendall(request.encode())
    head = b""
    while not head.endswith(b"\n"):
        c = s.recv(1)
        if not c:
            raise OSError("peer closed before header")
        head += c
    return head.decode().strip(), s


def peer_epoch(addr: str, port: int,
               timeout: float = 3.0) -> Tuple[int, int, str]:
    """``(epoch, total, digest)`` of the peer's in-memory commit."""
    head, s = _ask(addr, port, "EPOCH\n", timeout)
    s.close()
    parts = head.split()
    if len(parts) != 4 or parts[0] != "EPOCH":
        raise OSError(f"bad EPOCH response {head!r}")
    try:
        return int(parts[1]), int(parts[2]), parts[3]
    except ValueError as exc:
        # A reused port (another service answered) or a dying peer's
        # garbled header must take the same failover path as a refused
        # connection — the restore's OSError handling, never a crash.
        raise OSError(f"bad EPOCH response {head!r}") from exc


def fetch_shard(addr: str, port: int, epoch: int, index: int, count: int,
                timeout: float = 5.0) -> bytes:
    head, s = _ask(addr, port, f"SHARD {epoch} {index} {count}\n", timeout)
    try:
        parts = head.split()
        if len(parts) != 3 or parts[0] != "OK":
            raise OSError(f"peer refused shard: {head!r}")
        try:
            n = int(parts[1])
        except ValueError as exc:
            raise OSError(f"malformed shard header {head!r}") from exc
        digest = parts[2]
        data = b""
        while len(data) < n:
            c = s.recv(min(n - len(data), 1 << 16))
            if not c:
                raise OSError(
                    f"peer died mid-shard ({len(data)}/{n} bytes)")
            data += c
        if blob_digest(data) != digest:
            raise OSError("shard digest mismatch over the wire")
        return data
    finally:
        s.close()


# --------------------------------------------------------------- the plane
class StatePlane:
    """Per-rank resilient-state agent: in-memory committed epoch +
    overlap-scheduled durable shard writes + the peer/disk restore
    decision.  jax-free; thread-safe (commit from the train thread,
    chunk items from the engine cycle thread, shard serving from the
    server thread)."""

    def __init__(self, directory: str, rank: int = 0, world: int = 1,
                 engine=None, chunk_bytes: int = 1 << 20,
                 generation: int = 0, serve: bool = True,
                 declare: Optional[Callable[[dict], None]] = None,
                 io_retries: int = 3, io_backoff_ms: float = 50.0):
        self.directory = directory
        self.rank = max(0, int(rank))
        self.world = max(1, int(world))
        self.engine = engine
        self.chunk_bytes = max(1, int(chunk_bytes))
        self.generation = int(generation)
        self.io_retries = max(0, int(io_retries))
        self.io_backoff_ms = float(io_backoff_ms)
        os.makedirs(directory, exist_ok=True)
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._mem_epoch = -1
        self._mem_blob: Optional[bytes] = None
        self._mem_digest: Optional[str] = None
        # Current + previous committed blobs (epoch -> blob): the shard
        # server answers requests for EITHER, so a commit landing while
        # a joiner fetches does not strand the fetch (see
        # ShardServer._handle).
        self._mem_blobs: Dict[int, bytes] = {}
        self._durable_epoch = -1
        self._job: Optional[_WriteJob] = None
        self._declare = declare
        self._declaring = False          # one declare worker in flight
        self._declare_dirty = False      # re-declare after it returns
        self._last_commit_ts: Optional[float] = None   # monotonic
        # Observability (monitor checkpoint block + tests).
        self.commits = 0
        self.chunks_written = 0
        self.write_failures = 0
        self.disk_reads = 0          # shard FILES opened by restore
        self.peer_shards_fetched = 0
        self.restore_fallbacks = 0   # peer restores that fell back to disk
        self.last_restore_source: Optional[str] = None
        self.quarantined: List[str] = []
        # Fault harness: cached only when armed (zero-cost unarmed, the
        # same contract the controller keeps).
        from ..testing import faults as _faults
        self._fire = _faults.fire if _faults.armed() else None
        self.server = ShardServer(self) if serve else None

    # ------------------------------------------------------------- commits
    def commit(self, state: Optional[Dict] = None,
               blob: Optional[bytes] = None, epoch: Optional[int] = None,
               wait: bool = False, timeout: float = 30.0) -> int:
        """Commit one epoch: publish it in memory (survivors serve it to
        re-joiners immediately) and stream the 1/N durable shard through
        the engine's checkpoint lane (inline when no engine is attached).
        Returns the epoch id."""
        if blob is None:
            if state is None:
                raise ValueError("commit needs a state dict or a blob")
            blob = encode_state(state)
        with self._lock:
            if epoch is None:
                epoch = max(self._mem_epoch, self._durable_epoch) + 1
            self._mem_epoch = int(epoch)
            self._mem_blob = blob
            self._mem_digest = blob_digest(blob)
            self._mem_blobs[int(epoch)] = blob
            for old in sorted(self._mem_blobs)[:-2]:
                del self._mem_blobs[old]      # keep current + previous
            self._last_commit_ts = time.monotonic()
            self.commits += 1
            prev, self._job = self._job, None
            job = _WriteJob(self, int(epoch), blob)
            self._job = job
        if prev is not None and not prev.done:
            # Newest epoch wins: a fast commit cadence (autoscale
            # oscillation) must not queue a backlog of doomed epochs.
            prev.cancel()
        items = job.chunk_items(self.chunk_bytes)
        eng = self.engine
        submit = getattr(eng, "submit_checkpoint_io", None) if eng else None
        if submit is not None:
            submit(items)
        else:
            for it in items:
                it.run()
        self.declare_async()
        if wait:
            self.wait_durable(int(epoch), timeout)
        return int(epoch)

    def declare_async(self) -> None:
        """Publish this rank's state record to the rendezvous KV off the
        calling (training) thread: the declare is advisory metadata over
        HTTP, and an unreachable driver — exactly the churn this
        subsystem exists for — must not turn every commit into a
        connect-timeout stall.  Latest-wins: at most one worker in
        flight, a commit landing meanwhile re-declares once more."""
        if self._declare is None:
            return
        with self._lock:
            if self._declaring:
                self._declare_dirty = True
                return
            self._declaring = True

        def _run():
            while True:
                try:
                    self._declare(self.describe())
                except Exception as exc:  # noqa: BLE001 - advisory
                    log.warning("state plane: declare failed: %s", exc)
                with self._lock:
                    if self._declare_dirty:
                        self._declare_dirty = False
                        continue
                    self._declaring = False
                    return

        threading.Thread(target=_run, daemon=True,
                         name="hvd-tpu-state-declare").start()

    def wait_durable(self, epoch: int, timeout: float = 30.0) -> bool:
        """Block until ``epoch`` (or newer) is durable on disk; False on
        timeout or if the epoch's write failed/was superseded-then-failed."""
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._durable_epoch < epoch:
                job = self._job
                if job is not None and job.epoch >= epoch and (
                        job.failed is not None or job.canceled):
                    return False
                if job is None or job.epoch < epoch:
                    # No write in flight can ever reach this epoch.
                    if self._durable_epoch < epoch:
                        return False
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._cv.wait(min(0.2, left))
            return True

    def _job_durable(self, job: _WriteJob) -> None:
        with self._cv:
            if job.epoch > self._durable_epoch:
                self._durable_epoch = job.epoch
            if self._job is job:
                self._job = None
            self._cv.notify_all()

    def _job_failed(self, job: _WriteJob, exc: BaseException) -> None:
        with self._cv:
            self.write_failures += 1
            if self._job is job:
                self._job = None
            self._cv.notify_all()
        log.error(
            "state plane: abandoning checkpoint epoch %d on rank %d "
            "(shard write failed after %d retries: %s); durable state "
            "remains epoch %d", job.epoch, self.rank, self.io_retries,
            exc, self._durable_epoch)

    # -------------------------------------------------------------- reading
    @property
    def epoch(self) -> int:
        with self._lock:
            return self._mem_epoch

    @property
    def durable_epoch(self) -> int:
        with self._lock:
            return self._durable_epoch

    def memory_state(self) -> Tuple[int, Optional[bytes], Optional[str]]:
        with self._lock:
            return self._mem_epoch, self._mem_blob, self._mem_digest

    def blob_for(self, epoch: int) -> Optional[bytes]:
        """The committed blob for ``epoch`` — current or the retained
        previous one (the mid-fetch-commit guarantee), else None."""
        with self._lock:
            return self._mem_blobs.get(int(epoch))

    def describe(self) -> dict:
        """The rendezvous state record a rank declares: epoch + where its
        shard server listens + the blob identity a joiner verifies
        against."""
        with self._lock:
            return {
                "epoch": self._mem_epoch,
                "durable_epoch": self._durable_epoch,
                "generation": self.generation,
                "port": self.server.port if self.server else 0,
                "digest": self._mem_digest,
                "total": len(self._mem_blob) if self._mem_blob else 0,
            }

    def status(self) -> dict:
        """The monitor's ``checkpoint`` block (snapshot + /health)."""
        with self._lock:
            age = (round(time.monotonic() - self._last_commit_ts, 3)
                   if self._last_commit_ts is not None else None)
            return {
                "epoch": self._mem_epoch,
                "durable_epoch": self._durable_epoch,
                "last_commit_age_s": age,
                "commits": self.commits,
                "chunks_written": self.chunks_written,
                "write_failures": self.write_failures,
                "last_restore_source": self.last_restore_source,
                "disk_reads": self.disk_reads,
                "peer_shards_fetched": self.peer_shards_fetched,
            }

    # -------------------------------------------------------------- restore
    def restore(self, peers: Sequence[Tuple[str, int]] = (),
                decode: bool = True):
        """The restore decision: peers first, disk as the fallback.

        ``peers``: ``(addr, port)`` shard-server endpoints of candidate
        survivors.  When at least one reachable survivor holds an epoch
        NEWER than this rank's in-memory epoch, the state is allgathered
        as 1/K shards from the K newest-epoch survivors (any survivor
        re-serves a dead peer's shard) and verified against their blob
        digest — zero disk reads.  Otherwise (no quorum: no peers
        reachable, or none newer) the manifest path restores the newest
        complete on-disk epoch, quarantining corrupt shards with rank
        attribution.  Returns ``(state, epoch, source)`` with source
        ``"peer"`` or ``"disk"``."""
        result = None
        if peers:
            result = self._restore_from_peers(peers)
            if result is None and self._peer_attempted:
                self.restore_fallbacks += 1
        if result is None:
            blob, epoch = self._restore_from_disk()
            source = "disk"
        else:
            blob, epoch = result
            source = "peer"
        with self._lock:
            cur_epoch, cur_blob = self._mem_epoch, self._mem_blob
        if cur_blob is not None and epoch <= cur_epoch:
            # Never roll a rank BACKWARDS: a peer restore that degraded
            # to disk (the declared-newer survivor died mid-fetch) can
            # recover an epoch older than what this rank already holds
            # in memory — keep our own state (source "memory"), or a
            # re-ranked rank 0 would sync() the rollback to the fleet.
            log.warning(
                "state plane: recovered epoch %d from %s is not newer "
                "than this rank's in-memory epoch %d; keeping own state",
                epoch, source, cur_epoch)
            with self._lock:
                self.last_restore_source = "memory"
            return ((decode_state(cur_blob) if decode else cur_blob),
                    cur_epoch, "memory")
        with self._lock:
            self._mem_epoch = epoch
            self._mem_blob = blob
            self._mem_digest = blob_digest(blob)
            self._mem_blobs[int(epoch)] = blob
            for old in sorted(self._mem_blobs)[:-2]:
                del self._mem_blobs[old]
            self.last_restore_source = source
        return (decode_state(blob) if decode else blob), epoch, source

    _peer_attempted = False

    def _restore_from_peers(self, peers) -> Optional[Tuple[bytes, int]]:
        from concurrent.futures import ThreadPoolExecutor
        self._peer_attempted = False
        my_epoch = self.epoch

        # Probe every candidate CONCURRENTLY: rendezvous records of
        # departed hosts each cost a full connect timeout, and a serial
        # sweep would delay the restore by seconds per corpse.
        def _probe(peer):
            addr, port = peer
            try:
                e, total, digest = peer_epoch(addr, port)
            except OSError:
                return None
            return (addr, port, e, total, digest)

        with ThreadPoolExecutor(max_workers=min(16, len(peers))) as pool:
            probed = list(pool.map(_probe, peers))
        alive = [a for a in probed
                 if a is not None and a[2] > my_epoch and a[3] > 0]
        if not alive:
            return None             # no quorum of newer-epoch survivors
        self._peer_attempted = True
        best = max(a[2] for a in alive)
        donors = [a for a in alive if a[2] == best]
        total, digest = donors[0][3], donors[0][4]
        k = len(donors)

        # Fetch the K shards concurrently (the allgather shape that makes
        # 1/K sharding a wall-clock win, not just a load spread), round-
        # robin primary with every other donor as the fallback: a
        # survivor dying mid-restore costs one re-fetch, not the restore.
        def _fetch(i):
            order = [donors[(i + j) % k] for j in range(k)]
            for addr, port, _e, _t, _d in order:
                try:
                    return fetch_shard(addr, port, best, i, k)
                except OSError as exc:
                    log.warning(
                        "state plane: peer %s:%d failed serving shard "
                        "%d/%d of epoch %d (%s); trying the next survivor",
                        addr, port, i, k, best, exc)
            return None

        with ThreadPoolExecutor(max_workers=min(8, k)) as pool:
            shards = list(pool.map(_fetch, range(k)))
        self.peer_shards_fetched += sum(1 for s in shards if s is not None)
        if any(s is None for s in shards):
            log.warning("state plane: no survivor could serve every "
                        "shard of epoch %d; falling back to disk", best)
            return None
        blob = b"".join(shards)[:total]
        if blob_digest(blob) != digest:
            log.error("state plane: reassembled peer epoch %d failed its "
                      "digest check; falling back to disk", best)
            return None
        return blob, best

    def _restore_from_disk(self) -> Tuple[bytes, int]:
        """Manifest path: newest complete epoch wins; a corrupt shard
        quarantines the file (``.quarantined``, attributed to the rank
        that wrote it) and sends the search to the next older epoch."""
        for epoch in reversed(list_epochs(self.directory)):
            manifests = epoch_manifests(self.directory, epoch)
            if manifests is None:
                continue
            world = manifests[0]["world"]
            d = _epoch_dir(self.directory, epoch)
            parts: List[bytes] = []
            ok = True
            for rec in manifests:
                path = os.path.join(
                    d, _shard_base(rec["rank"], world) + ".bin")
                try:
                    with open(path, "rb") as fh:
                        data = fh.read()
                    self.disk_reads += 1
                except OSError:
                    ok = False
                    break
                if (len(data) != rec["nbytes"]
                        or blob_digest(data) != rec["digest"]):
                    self._quarantine(path, rec, epoch)
                    ok = False
                    break
                parts.append(data)
            if not ok:
                continue
            blob = b"".join(parts)[:manifests[0]["total"]]
            if blob_digest(blob) != manifests[0]["blob_digest"]:
                log.error("state plane: epoch %d reassembly failed its "
                          "blob digest; skipping", epoch)
                continue
            return blob, epoch
        raise FileNotFoundError(
            f"state plane: no restorable epoch under {self.directory!r} "
            f"(no peers with newer state, no complete manifest on disk)")

    def _quarantine(self, path: str, rec: dict, epoch: int) -> None:
        target = path + ".quarantined"
        try:
            os.replace(path, target)
        except OSError:
            target = path + " (unmovable)"
        self.quarantined.append(target)
        log.error(
            "state plane: CORRUPT shard quarantined — epoch %d shard "
            "written by rank %d fails its manifest digest (%s); moved to "
            "%s; trying the next older epoch", epoch, rec.get("rank"),
            rec.get("digest"), target)

    # ------------------------------------------------------------ lifecycle
    def set_declare(self, declare: Optional[Callable[[dict], None]]):
        self._declare = declare

    def flush(self, timeout: float = 30.0) -> bool:
        """Drain the in-flight durable write (clean shutdown)."""
        with self._lock:
            job = self._job
        if job is None:
            return True
        return self.wait_durable(job.epoch, timeout)

    def close(self) -> None:
        if self.server is not None:
            self.server.stop()
            self.server = None


# ----------------------------------------------- generation-surviving planes
_registry: Dict[str, StatePlane] = {}
_registry_lock = threading.Lock()


def obtain(directory: str, rank: int, world: int, engine=None,
           chunk_bytes: int = 1 << 20) -> StatePlane:
    """The engine's constructor hook: ONE plane per checkpoint directory
    per process, surviving elastic re-init exactly like the per-host
    agent — the in-memory committed epoch (what survivors serve to
    re-joiners) must outlive the generation that committed it.  Re-init
    re-binds rank/world/engine to the new assignment; the shard server
    and the epoch persist."""
    with _registry_lock:
        plane = _registry.get(directory)
        if plane is None:
            plane = StatePlane(directory, rank=rank, world=world,
                               engine=engine, chunk_bytes=chunk_bytes)
            _registry[directory] = plane
        else:
            plane.rank, plane.world = max(0, int(rank)), max(1, int(world))
            plane.engine = engine
            plane.chunk_bytes = max(1, int(chunk_bytes))
            if plane.server is None:
                plane.server = ShardServer(plane)
        return plane


# ------------------------------------------------- elastic-state integration
def attach(state, plane: Optional[StatePlane] = None):
    """Attach the live engine's state plane to an elastic ``State`` (the
    ``@hvd.elastic.run`` wrapper calls this whenever HOROVOD_CKPT_DIR is
    configured): ``state.commit()`` then also streams the durable shard,
    and the rank's epoch is declared in the rendezvous metadata after
    every commit.  No-op (returns None) when no plane is armed."""
    if plane is None:
        from ..common import basics
        eng = getattr(basics._get_state(), "engine", None)
        plane = getattr(eng, "stateplane", None) if eng is not None else None
    if plane is None:
        return None
    state._stateplane = plane
    addr = os.environ.get("HOROVOD_RENDEZVOUS_ADDR")
    port = os.environ.get("HOROVOD_RENDEZVOUS_PORT")
    if addr and port:
        from . import rendezvous as rdv
        from . import worker as ew
        ident = ew.identity()
        if ew._current_version is not None:
            plane.generation = int(ew._current_version)
        plane.set_declare(
            lambda rec, a=addr, p=int(port), i=ident:
            rdv.declare_state(a, p, i, rec))
        plane.declare_async()
    return plane


def maybe_restore(state, plane: StatePlane) -> Optional[str]:
    """Peer-first restore for a (re-)joining rank: read the rendezvous
    state directory, and when any survivor declares a newer epoch, pull
    the committed state from the survivors' shard servers (disk manifest
    as the fallback) and load it into ``state``.  Returns the restore
    source ('peer'/'disk') or None when this rank is already current."""
    addr = os.environ.get("HOROVOD_RENDEZVOUS_ADDR")
    port = os.environ.get("HOROVOD_RENDEZVOUS_PORT")
    if not addr or not port:
        return None
    from . import rendezvous as rdv
    from . import worker as ew
    ident = ew.identity()
    try:
        records = rdv.state_directory(addr, int(port))
    except OSError:
        return None
    best = plane.epoch
    peers = []
    for who, rec in records.items():
        if who == ident or not rec.get("port"):
            continue
        if int(rec.get("epoch", -1)) > plane.epoch:
            peers.append((who.rsplit(":", 1)[0], int(rec["port"])))
            best = max(best, int(rec["epoch"]))
    if not peers:
        return None
    try:
        data, epoch, source = plane.restore(peers=peers)
    except FileNotFoundError:
        return None
    if source == "memory":
        # Recovery found nothing newer than what this rank already
        # holds: leave the State object untouched.
        return None
    loader = getattr(state, "load_recovered", None)
    if loader is not None:
        # The State subclass owns the load (ISSUE 15): JaxState rebuilds
        # device arrays and re-slices a sharded optimizer's own 1/N
        # shard — the REAL jax path riding the peer shard fetch directly
        # instead of waiting for the object-level sync() to cover it.
        loader(data)
    else:
        for k, v in data.items():
            setattr(state, k, v)
        state.save()
    log.warning("state plane: rank restored epoch %d from %s "
                "(declared best %d)", epoch, source, best)
    return source
