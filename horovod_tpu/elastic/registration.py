"""Worker result registry + host blacklist for the elastic driver.

Parity: reference ``horovod/runner/elastic/registration.py``
(``WorkerStateRegistry``) — records each worker's terminal state per
generation and blacklists hosts that produced failures so rank
re-assignment skips them (SURVEY.md §3.4 driver side).
"""

from __future__ import annotations

import threading
from typing import Dict, Set

READY = "READY"
SUCCESS = "SUCCESS"
FAILURE = "FAILURE"
# Clean departure (drain → LEAVE → exit 0, or a driver-released identity):
# terminal like SUCCESS/FAILURE, but it is neither a job-completion signal
# nor a blacklisting failure — the host stays schedulable.
LEFT = "LEFT"


class WorkerStateRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._states: Dict[str, str] = {}       # identity -> state
        self._blacklist: Set[str] = set()       # hostnames
        self._failures: Dict[str, int] = {}     # hostname -> count

    def record_ready(self, identity: str):
        with self._lock:
            self._states[identity] = READY

    def record_success(self, identity: str):
        with self._lock:
            self._states[identity] = SUCCESS

    def record_failure(self, identity: str):
        host = identity.rsplit(":", 1)[0]
        with self._lock:
            self._states[identity] = FAILURE
            self._failures[host] = self._failures.get(host, 0) + 1
            self._blacklist.add(host)

    def record_left(self, identity: str):
        """Clean-exit classification: a worker that exited 0 because the
        driver drained it (autoscale scale-in / straggler evict → clean
        LEAVE) or released it (host removed from a generation).  NOT a
        success — it must not end the job — and NOT a failure: the host
        is never blacklisted for an orderly departure, so it stays
        eligible for a later scale-out."""
        with self._lock:
            self._states[identity] = LEFT

    def state_of(self, identity: str) -> str:
        with self._lock:
            return self._states.get(identity, "")

    def is_blacklisted(self, hostname: str) -> bool:
        with self._lock:
            return hostname in self._blacklist

    def blacklist(self) -> Set[str]:
        with self._lock:
            return set(self._blacklist)

    def failure_count(self, hostname: str) -> int:
        with self._lock:
            return self._failures.get(hostname, 0)

    def success_count(self) -> int:
        with self._lock:
            return sum(1 for s in self._states.values() if s == SUCCESS)
