"""Elastic state objects and the retrying run wrapper.

Reference files mirrored: ``horovod/common/elastic.py`` (State, run_fn),
``horovod/torch/elastic/state.py`` (TorchState analogue -> ``JaxState``).
See SURVEY.md §3.4 for the control flow being reproduced.
"""

from __future__ import annotations

import copy
import functools
from typing import Any, Callable, Dict, Optional

import jax

# Historical home of HorovodInternalError; the hierarchy now lives in the
# jax-free common/exceptions.py (the controller and fault harness raise
# typed subclasses without importing jax).  Re-exported here so existing
# ``from horovod_tpu.elastic.state import HorovodInternalError`` imports —
# including the torch/elastic binding — keep working.
from ..common.exceptions import (  # noqa: F401  (re-export)
    ControlPlaneError, DrainRequested, HorovodInternalError,
    HostsUpdatedInterrupt, PeerFailureError, PeerLeftInterrupt,
    RoundTimeoutError,
)

# HostsUpdatedInterrupt (and the new DrainRequested / PeerLeftInterrupt)
# moved to the jax-free common/exceptions.py with the rest of the control-
# flow taxonomy — the controller, the engine and the autoscaling stack
# raise them without importing jax.  Re-exported above so every historical
# ``from horovod_tpu.elastic.state import HostsUpdatedInterrupt`` import
# keeps seeing the ONE class.


class State:
    """Base elastic state: commit/restore/sync + reset listeners.

    Matches the reference's ``horovod.common.elastic.State`` surface:
    ``register_reset_callbacks``, ``on_reset``, ``commit``, ``restore``,
    ``sync``.
    """

    def __init__(self, **kwargs):
        self._reset_callbacks = []
        self._kwargs = kwargs
        for k, v in kwargs.items():
            setattr(self, k, v)

    def register_reset_callbacks(self, callbacks):
        self._reset_callbacks.extend(callbacks)

    def on_reset(self):
        for cb in self._reset_callbacks:
            cb()

    def on_hosts_updated(self, timestamp, update_res):
        pass

    def commit(self):
        self.save()
        # Resilient state plane (ISSUE 14): when HOROVOD_CKPT_DIR armed a
        # plane (attached by the @run wrapper), every commit also streams
        # this rank's 1/N durable shard through the engine's checkpoint
        # lane and publishes the epoch for peer-to-peer restore.  The
        # committed attribute dict is exactly what restore() rolls back
        # to, so it is exactly what becomes durable.
        sp = getattr(self, "_stateplane", None)
        saved = getattr(self, "_saved_state", None)
        if sp is not None and saved:
            try:
                sp.commit(state=saved)
            except Exception as exc:  # noqa: BLE001 - durability must
                # never fail the training step; the previous epoch stays.
                from ..utils.logging import get_logger
                get_logger().error("state plane commit failed: %s", exc)
        self.check_host_updates()

    def check_host_updates(self):
        # Hooked by the worker-notification client in multi-process mode.
        notifier = getattr(self, "_notification_manager", None)
        if notifier is not None:
            notifier.raise_if_updated()

    def should_commit(self) -> bool:
        """Checkpoint pacing (ISSUE 12): True when the elastic driver has
        requested an immediate state commit (a ``COMMIT`` notification —
        sent just before it executes a scale or preemption decision, so
        the last commit predates the world change by milliseconds, not a
        timer period).  Consult it alongside any periodic cadence::

            if state.should_commit() or batch % commit_every == 0:
                state.commit()

        Consumed on read; False when no notification manager is attached
        (single-process / non-elastic runs)."""
        notifier = getattr(self, "_notification_manager", None)
        if notifier is None:
            return False
        return bool(notifier.consume_commit_request())

    def save(self):
        raise NotImplementedError

    def restore(self):
        raise NotImplementedError

    def sync(self):
        raise NotImplementedError

    def load_recovered(self, data: Dict[str, Any]):
        """Load a state dict recovered by the state plane's peer/disk
        restore (``stateplane.maybe_restore``) into the LIVE attributes
        and re-save.  The base implementation is a raw attribute load;
        :class:`JaxState` overrides it to rebuild device arrays (and
        re-slice a sharded optimizer's own 1/N shard) — the hook that
        wires the REAL jax path through the peer shard fetch instead of
        leaving it to the object-level ``sync()``."""
        for k, v in data.items():
            setattr(self, k, v)
        self.save()


class ObjectState(State):
    """Elastic state of plain Python attributes, synced via
    ``broadcast_object`` (reference: ``horovod/common/elastic.py``)."""

    def __init__(self, bcast_object: Optional[Callable] = None, **kwargs):
        if bcast_object is None:
            from ..ops.eager import broadcast_object as bcast_object
        self._bcast_object = bcast_object
        self._saved_state: Dict[str, Any] = {}
        super().__init__(**kwargs)
        self.save()

    def save(self):
        self._saved_state = {k: copy.deepcopy(getattr(self, k))
                             for k in self._kwargs}

    def restore(self):
        for k, v in self._saved_state.items():
            setattr(self, k, copy.deepcopy(v))

    def sync(self):
        if self._saved_state:
            synced = self._bcast_object(self._saved_state, root_rank=0)
            for k, v in synced.items():
                setattr(self, k, v)
                self._saved_state[k] = copy.deepcopy(v)


class JaxState(ObjectState):
    """Elastic state of a JAX train state (params/opt_state pytrees).

    The analogue of the reference's ``TorchState``: pytree leaves are saved
    to host memory on ``commit`` (cheap, async device→host), restored to
    device on ``restore``, and rank-0-broadcast on ``sync``.

    **Sharded optimizer states** (ISSUE 15): a value that is a
    ``DistributedOptimizer(sharded=True)`` eager state (1/world of the
    optimizer state on this rank) saves as its rank-INVARIANT gathered
    form — all ranks then serialize the identical blob, which is what the
    state plane's shard digests require — and every load path (restore /
    sync / the peer-fetch ``load_recovered``) re-slices exactly this
    rank's own 1/N shard back out.  With the state plane armed, a
    re-joiner's peer shard fetch therefore restores its optimizer slice
    shard-natively instead of re-sharding a replicated copy.

    Usage:
        state = JaxState(params=params, opt_state=opt_state, epoch=0, batch=0)
    """

    def __init__(self, **kwargs):
        self._tree_keys = [k for k, v in kwargs.items()
                           if _is_pytree_of_arrays(v)]
        super().__init__(**kwargs)

    def save(self):
        self._saved_state = {}
        for k in self._kwargs:
            v = getattr(self, k)
            if hasattr(v, "hvd_sharded_saveable"):
                self._saved_state[k] = v.hvd_sharded_saveable()
            elif k in self._tree_keys:
                self._saved_state[k] = jax.tree_util.tree_map(
                    lambda x: jax.device_get(x), v)
            else:
                self._saved_state[k] = copy.deepcopy(v)

    @staticmethod
    def _revive(v):
        """A saved value back to its live form: sharded saveables become
        this rank's shard state, anything else passes through (``None``
        means the sharded layout no longer fits — callers keep the raw
        saveable and the user re-inits for the new world)."""
        from ..jax.optimizer import is_sharded_saveable, \
            load_sharded_saveable
        if is_sharded_saveable(v):
            from ..common import basics
            loaded = load_sharded_saveable(v, basics.rank(), basics.size())
            if loaded is not None:
                return loaded
        return None

    def restore(self):
        for k, v in self._saved_state.items():
            revived = self._revive(v)
            if revived is not None:
                setattr(self, k, revived)
            elif k in self._tree_keys:
                setattr(self, k, jax.tree_util.tree_map(jax.numpy.asarray, v))
            else:
                setattr(self, k, copy.deepcopy(v))

    def sync(self):
        if not self._saved_state:
            return
        synced = self._bcast_object(self._saved_state, root_rank=0)
        for k, v in synced.items():
            revived = self._revive(v)
            if revived is not None:
                setattr(self, k, revived)
            elif k in self._tree_keys:
                setattr(self, k, jax.tree_util.tree_map(jax.numpy.asarray, v))
            else:
                setattr(self, k, copy.deepcopy(v))
            self._saved_state[k] = v

    def load_recovered(self, data):
        """Peer/disk-recovered state into live device arrays: tree keys
        come back as device arrays, a sharded optimizer saveable comes
        back as THIS rank's 1/N shard (the shard-native restore).

        The recovered dict itself becomes the new ``_saved_state`` —
        NEVER ``self.save()`` here: a sharded save gathers collectively,
        and only the stale (re-joining) rank runs this path, so a
        collective would deadlock against the survivors."""
        for k, v in data.items():
            revived = self._revive(v)
            if revived is not None:
                setattr(self, k, revived)
            elif k in self._tree_keys and _is_pytree_of_arrays(v):
                setattr(self, k, jax.tree_util.tree_map(jax.numpy.asarray, v))
            else:
                setattr(self, k, v)
            self._saved_state[k] = v


def _is_pytree_of_arrays(v) -> bool:
    leaves = jax.tree_util.tree_leaves(v)
    return bool(leaves) and all(
        hasattr(leaf, "shape") and hasattr(leaf, "dtype") for leaf in leaves)


def run(func: Callable) -> Callable:
    """``@hvd.elastic.run`` — retrying elastic train-loop wrapper.

    Control flow mirrors SURVEY.md §3.4: sync, run; on
    ``HorovodInternalError`` restore to last commit; on
    ``HostsUpdatedInterrupt`` keep params; either way re-init the runtime
    (which on TPU rebuilds the mesh and recompiles) and retry.
    """
    @functools.wraps(func)
    def wrapper(state: State, *args, **kwargs):
        import os
        from ..common import basics
        if os.environ.get("HOROVOD_ELASTIC"):
            from . import worker
            worker.attach_notification_manager(state)
        # Resilient state plane (ISSUE 14): HOROVOD_CKPT_DIR arms a
        # per-rank plane on the engine — attach it so state.commit()
        # streams durable shards, and re-attach after every re-init (the
        # reset builds a fresh engine, hence a fresh plane).
        from . import stateplane as _sp
        plane = _sp.attach(state)
        reset_required = False
        skip_sync = False
        # Peer restore applies only while this rank's live state is
        # actually STALE: a fresh process (initial params) or one that
        # just rolled back to its last commit after a fault.  A survivor
        # re-entering on a clean HostsUpdatedInterrupt holds the fleet's
        # CURRENT state — its plane epoch may still lag a peer's (commit
        # pings land on skewed cadence), and pulling that peer's older
        # commit would roll live training backwards (and, re-ranked to
        # rank 0, sync() the rollback fleet-wide).
        stale = True
        while True:
            if reset_required:
                _reset(state)
                plane = _sp.attach(state)
                state.on_reset()
            try:
                if not skip_sync:
                    if plane is not None and stale:
                        # Peer-first restore: a (re-)joining rank whose
                        # epoch lags the survivors' pulls the committed
                        # state from their shard servers (disk manifest
                        # as the fallback) BEFORE sync — so even a
                        # re-ranked rank 0 broadcasts recovered state,
                        # never its own stale/empty one.
                        _sp.maybe_restore(state, plane)
                    state.sync()
                stale = False
                return func(state, *args, **kwargs)
            except HorovodInternalError:
                state.restore()
                skip_sync = False
                stale = True
            except DrainRequested:
                # The driver asked this worker to drain (autoscale
                # scale-in / straggler evict): the batch that just
                # committed is the last one — shut down, which sends the
                # clean LEAVE (protocol v6) so survivors see an orderly
                # departure, and return.  Exit 0 is the contract the
                # driver's clean-exit classification keys on.
                basics.shutdown()
                return None
            except HostsUpdatedInterrupt as e:
                skip_sync = e.skip_sync
            reset_required = True

    def _reset(state: State):
        from ..common import basics
        basics.shutdown()
        basics.init()

    return wrapper
