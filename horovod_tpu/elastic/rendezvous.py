"""Versioned rendezvous KV service for elastic training.

Parity: the reference's launcher-hosted HTTP KV store
(``horovod/runner/http/http_server.py``) + the elastic rendezvous layer
(``horovod/runner/elastic/rendezvous.py``) — SURVEY.md §2b P9/P10, §3.4.
The driver publishes a monotonically-versioned assignment table
(identity ``host:local_rank`` → rank/size/controller address); workers
long-poll for the first version ≥ their requested minimum, which is how a
worker re-entering after a reset is guaranteed to land in the NEW
generation rather than re-joining the stale one.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional
from urllib.parse import parse_qs, urlparse


class RendezvousServer:
    """Driver-side server: assignment table + worker notification registry."""

    def __init__(self, addr: str = "0.0.0.0"):
        self._lock = threading.Lock()
        self._version = 0
        self._assignments: Dict[str, dict] = {}
        self._notify_ports: Dict[str, int] = {}
        # State-plane metadata (ISSUE 14): identity -> declared state
        # record ({"epoch", "port", "digest", ...}) — how a re-joining
        # rank discovers which survivors hold a newer committed epoch and
        # where their shard servers listen, BEFORE deciding peer-vs-disk
        # restore.  Plain last-writer-wins KV; records survive generations
        # (a survivor's epoch is exactly what outlives the world change).
        self._state_records: Dict[str, dict] = {}
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def do_GET(self):
                url = urlparse(self.path)
                parts = [p for p in url.path.split("/") if p]
                with outer._lock:
                    if parts[:1] == ["version"]:
                        return self._json({"version": outer._version})
                    if parts[:1] == ["state"]:
                        return self._json(
                            {"state": dict(outer._state_records)})
                    if len(parts) == 2 and parts[0] == "assign":
                        identity = parts[1]
                        q = parse_qs(url.query)
                        min_v = int(q.get("min_version", ["0"])[0])
                        if (outer._version >= min_v
                                and identity in outer._assignments):
                            a = dict(outer._assignments[identity])
                            a["version"] = outer._version
                            return self._json(a)
                        return self._json({"pending": True}, code=404)
                return self._json({"error": "not found"}, code=404)

            def do_PUT(self):
                parts = [p for p in self.path.split("/") if p]
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n).decode() if n else ""
                if len(parts) == 2 and parts[0] == "notify":
                    with outer._lock:
                        outer._notify_ports[parts[1]] = int(body)
                    return self._json({"ok": True})
                if len(parts) == 2 and parts[0] == "state":
                    try:
                        rec = json.loads(body)
                    except ValueError:
                        return self._json({"error": "bad json"}, code=400)
                    with outer._lock:
                        outer._state_records[parts[1]] = rec
                    return self._json({"ok": True})
                return self._json({"error": "not found"}, code=404)

            def _json(self, obj, code=200):
                data = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        self._httpd = ThreadingHTTPServer((addr, 0), Handler)
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def version(self) -> int:
        with self._lock:
            return self._version

    def publish(self, assignments: Dict[str, dict]) -> int:
        """Atomically install a new generation; returns its version."""
        with self._lock:
            self._version += 1
            self._assignments = dict(assignments)
            return self._version

    def notification_ports(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._notify_ports)

    def state_records(self) -> Dict[str, dict]:
        with self._lock:
            return dict(self._state_records)

    def drop_state(self, identity: str) -> None:
        """Prune a departed rank's state record (the driver calls this
        when it classifies an exit): a joiner must not waste a connect
        timeout probing a corpse's shard server."""
        with self._lock:
            self._state_records.pop(identity, None)

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


# ------------------------------------------------------------- worker client
def fetch_assignment(addr: str, port: int, identity: str,
                     min_version: int = 0,
                     timeout_s: float = 600.0) -> dict:
    """Long-poll the driver for this identity's assignment at version
    ≥ ``min_version`` (blocks while the driver re-forms the world)."""
    import http.client
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            conn = http.client.HTTPConnection(addr, port, timeout=10)
            conn.request("GET", f"/assign/{identity}?min_version={min_version}")
            resp = conn.getresponse()
            data = resp.read()
            conn.close()
            if resp.status == 200:
                return json.loads(data)
        except OSError:
            pass
        time.sleep(0.2)
    raise TimeoutError(
        f"rendezvous: no assignment for {identity} (min_version="
        f"{min_version}) within {timeout_s}s")


def register_notification_port(addr: str, port: int, identity: str,
                               notify_port: int):
    import http.client
    conn = http.client.HTTPConnection(addr, port, timeout=10)
    conn.request("PUT", f"/notify/{identity}", body=str(notify_port))
    conn.getresponse().read()
    conn.close()


def declare_state(addr: str, port: int, identity: str, record: dict,
                  timeout: float = 3.0):
    """Publish this rank's state-plane record (epoch + shard-server port
    + blob identity) to the driver's rendezvous KV — called after every
    commit (off the training thread; short timeout: advisory metadata),
    so survivors' declared epochs are current when a re-joining rank
    reads the directory."""
    import http.client
    conn = http.client.HTTPConnection(addr, port, timeout=timeout)
    conn.request("PUT", f"/state/{identity}", body=json.dumps(record))
    conn.getresponse().read()
    conn.close()


def state_directory(addr: str, port: int) -> Dict[str, dict]:
    """All declared state records (identity -> record)."""
    import http.client
    conn = http.client.HTTPConnection(addr, port, timeout=10)
    conn.request("GET", "/state")
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    if resp.status != 200:
        raise OSError(f"rendezvous /state returned {resp.status}")
    return json.loads(data).get("state", {})
