"""Host discovery for elastic training.

Parity: reference ``horovod/runner/elastic/discovery.py`` —
``HostDiscoveryScript`` executes the user's ``--host-discovery-script``
(lines of ``hostname`` or ``hostname:slots``) and the driver polls it for
changes.  On TPU the natural production implementation queries the GCE/TPU
metadata service for slice membership and preemption notices (SURVEY.md §5
"Failure detection"); the script interface is the cloud-agnostic contract.
"""

from __future__ import annotations

import dataclasses
import subprocess
from typing import Dict, List

from ..utils.logging import get_logger

log = get_logger()


@dataclasses.dataclass(frozen=True)
class DiscoveredHost:
    hostname: str
    slots: int


class HostDiscovery:
    def find_available_hosts_and_slots(self) -> List[DiscoveredHost]:
        raise NotImplementedError


class HostDiscoveryScript(HostDiscovery):
    def __init__(self, script: str, default_slots: int = 1):
        self.script = script
        self.default_slots = default_slots

    def find_available_hosts_and_slots(self) -> List[DiscoveredHost]:
        out = subprocess.run(self.script, shell=True, capture_output=True,
                             text=True, timeout=60)
        if out.returncode != 0:
            raise RuntimeError(
                f"host discovery script failed (rc={out.returncode}): "
                f"{out.stderr.strip()}")
        return self.parse(out.stdout)

    def parse(self, text: str) -> List[DiscoveredHost]:
        hosts: List[DiscoveredHost] = []
        seen: Dict[str, int] = {}
        for line in text.splitlines():
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            if ":" in line:
                name, slots = line.rsplit(":", 1)
                try:
                    h = DiscoveredHost(name.strip(), int(slots))
                except ValueError:
                    # Truncated/garbled output from a transient poll: skip
                    # the line rather than crash the elastic driver.
                    log.warning("host discovery: malformed line %r", line)
                    continue
            else:
                h = DiscoveredHost(line, self.default_slots)
            if h.hostname in seen:
                continue
            seen[h.hostname] = h.slots
            hosts.append(h)
        return hosts


class FixedHostDiscovery(HostDiscovery):
    """Static host list (used by tests and as a degenerate case)."""

    def __init__(self, hosts: List[DiscoveredHost]):
        self._hosts = list(hosts)

    def find_available_hosts_and_slots(self) -> List[DiscoveredHost]:
        return list(self._hosts)
