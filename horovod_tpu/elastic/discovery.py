"""Host discovery for elastic training.

Parity: reference ``horovod/runner/elastic/discovery.py`` —
``HostDiscoveryScript`` executes the user's ``--host-discovery-script``
(lines of ``hostname`` or ``hostname:slots``) and the driver polls it for
changes.  On TPU the natural production implementation queries the GCE/TPU
metadata service for slice membership and preemption notices (SURVEY.md §5
"Failure detection"); the script interface is the cloud-agnostic contract.
"""

from __future__ import annotations

import dataclasses
import subprocess
from typing import Dict, List, Set

from ..utils.logging import get_logger

log = get_logger()


@dataclasses.dataclass(frozen=True)
class DiscoveredHost:
    hostname: str
    slots: int


class HostDiscovery:
    def find_available_hosts_and_slots(self) -> List[DiscoveredHost]:
        raise NotImplementedError

    def preemption_notices(self) -> Set[str]:
        """Hostnames with an ACTIVE preemption notice (ISSUE 12): the host
        is still alive — it stays in the discovered set — but the platform
        has announced it will be reclaimed soon.  The elastic driver
        reacts by cordoning the host and DRAINING its workers (commit →
        clean LEAVE → exit, with a ``preempt_grace_s`` deadline falling
        back to termination) so the departure is orderly instead of a
        mid-collective crash.  Default: none — script/fixed discovery
        sources have no preemption signal."""
        return set()


class HostDiscoveryScript(HostDiscovery):
    def __init__(self, script: str, default_slots: int = 1):
        self.script = script
        self.default_slots = default_slots

    def find_available_hosts_and_slots(self) -> List[DiscoveredHost]:
        out = subprocess.run(self.script, shell=True, capture_output=True,
                             text=True, timeout=60)
        if out.returncode != 0:
            raise RuntimeError(
                f"host discovery script failed (rc={out.returncode}): "
                f"{out.stderr.strip()}")
        return self.parse(out.stdout)

    def parse(self, text: str) -> List[DiscoveredHost]:
        hosts: List[DiscoveredHost] = []
        seen: Dict[str, int] = {}
        for line in text.splitlines():
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            if ":" in line:
                name, slots = line.rsplit(":", 1)
                try:
                    h = DiscoveredHost(name.strip(), int(slots))
                except ValueError:
                    # Truncated/garbled output from a transient poll: skip
                    # the line rather than crash the elastic driver.
                    log.warning("host discovery: malformed line %r", line)
                    continue
            else:
                h = DiscoveredHost(line, self.default_slots)
            if h.hostname in seen:
                continue
            seen[h.hostname] = h.slots
            hosts.append(h)
        return hosts


class FixedHostDiscovery(HostDiscovery):
    """Static host list (used by tests and as a degenerate case)."""

    def __init__(self, hosts: List[DiscoveredHost]):
        self._hosts = list(hosts)

    def find_available_hosts_and_slots(self) -> List[DiscoveredHost]:
        return list(self._hosts)


class TPUMetadataDiscovery(HostDiscovery):
    """Slice membership + preemption notices from the TPU-VM metadata
    service (SURVEY.md §5: "discovery = GCE/TPU metadata + preemption
    notices" — the production discovery source on TPU, where the
    reference's ``--host-discovery-script`` is the cloud-agnostic shim).

    Endpoint contract (relative to ``base_url``, which defaults to the GCE
    metadata root and is injectable — ``HOROVOD_TPU_METADATA_URL`` — so
    tests run against a fake HTTP server):

    - ``instance/attributes/worker-network-endpoints`` — comma-separated
      worker records; the last ``:``-field of each record is the worker
      address (the TPU-VM format, which historically carried
      ``id:port:ip`` triples).  This is slice membership.
    - ``instance/attributes/preempted-workers`` — comma-separated worker
      addresses with an active preemption notice (404 or empty = none).
      A preempted worker STAYS in the discovered set (the hardware is
      still up) and is surfaced through :meth:`preemption_notices`
      instead: the elastic driver cordons the host and DRAINS its workers
      (state commit → clean LEAVE → exit 0, grace-bounded) so the
      departure takes the orderly path BEFORE the hardware disappears —
      never a mid-collective crash with a dead-peer verdict.  On a real
      deployment a per-host agent publishes this from its local
      ``instance/preempted`` + maintenance-event signals.

    ``slots_per_host`` defaults to 4 — the chips-per-host of current
    TPU-VM generations (v4/v5p/v5e/v6e all expose 4 local chips per
    worker) — and is overridable for asymmetric topologies.
    """

    _DEFAULT_BASE = "http://metadata.google.internal/computeMetadata/v1"

    def __init__(self, base_url: str = "", slots_per_host: int = 0,
                 timeout_s: float = 5.0):
        import os
        self.base_url = (base_url
                         or os.environ.get("HOROVOD_TPU_METADATA_URL", "")
                         or self._DEFAULT_BASE).rstrip("/")
        self.slots_per_host = slots_per_host or 4
        self.timeout_s = timeout_s
        # Latest preemption-notice set, refreshed by every membership
        # poll (the driver calls find_available... then reads notices).
        self._preempted: Set[str] = set()

    def _get(self, path: str, default: str = None) -> str:
        import urllib.error
        import urllib.request
        req = urllib.request.Request(
            f"{self.base_url}/{path}",
            headers={"Metadata-Flavor": "Google"})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
                return r.read().decode()
        except urllib.error.HTTPError as exc:
            if exc.code == 404 and default is not None:
                return default
            raise

    def find_available_hosts_and_slots(self) -> List[DiscoveredHost]:
        endpoints = self._get("instance/attributes/worker-network-endpoints")
        preempted = {
            p.strip()
            for p in self._get("instance/attributes/preempted-workers",
                               default="").split(",") if p.strip()}
        hosts: List[DiscoveredHost] = []
        seen = set()
        for rec in endpoints.split(","):
            rec = rec.strip()
            if not rec:
                continue
            addr = rec.rsplit(":", 1)[-1].strip()
            if not addr or addr in seen:
                continue
            seen.add(addr)
            if addr in preempted and addr not in self._preempted:
                log.warning("tpu metadata discovery: %s has a preemption "
                            "notice; the driver will drain it", addr)
            hosts.append(DiscoveredHost(addr, self.slots_per_host))
        # Notices only count for hosts still IN the membership: once the
        # hardware actually vanished, the membership change is the signal.
        self._preempted = preempted & seen
        return hosts

    def preemption_notices(self) -> Set[str]:
        return set(self._preempted)
