"""Closed-loop elastic autoscaling: the policy engine (no jax imports).

The wheel-turner the ROADMAP's "heavy traffic from millions of users"
north star was missing: PR 4's monitor computes cycle-time spread and
names stragglers, PR 5/this PR's control plane can lose and cleanly
release ranks, the elastic driver can re-rendezvous a resized world — and
this module decides WHEN.  Sergeev & Del Balso's operability stance
(PAPERS.md — stall warnings and autotuning as built-in operator tooling,
not runbooks) is the template: the system scales itself.

Shape: :class:`ScalePolicy` is a pure, clock-injected decision function —
``observe(summary, size, now)`` consumes one
:meth:`~..monitor.aggregator.RankAggregator.summary` record (cycle-time
spread + windowed EWMA trends + fleet queue depth + cycle counters) and
returns a typed :class:`ScaleDecision`.  No I/O, no threads, no wall
clock: the driver's orchestration loop (``elastic/driver.py``) owns
polling the rank-0 monitor endpoint and executing decisions
(``scale_out`` → the operator's scale command, ``evict``/``scale_in`` →
drain ping → clean LEAVE → discovery update), and tests drive the policy
with scripted summaries and a scripted clock.

Decision table (first match wins; see docs/elastic.md "Closed-loop
autoscaling" for the knob table):

=============  ======================================================
``preempt``    the discovery source posted a preemption notice for an
               assigned host (``observe(preempt_hosts=...)``): the
               hardware is going away on the platform's schedule, so
               the decision OUTRANKS every load signal AND the cooldown
               window — waiting is not an option — and opens a fresh
               cooldown so the shrink isn't immediately second-guessed
               by a queue-depth scale-out
``evict``      the SAME rank has been the slowest for ``persistence``
               consecutive observations AND its mean cycle time is ≥
               ``straggler_factor`` × the median of the other ranks —
               a persistent straggler gates the whole fleet (the
               Horovod paper's diagnosis), so drain it and let the
               world heal without it
``scale_out``  fleet queue depth trends up (``queue_depth_trend`` >
               ``queue_trend_up``) or sits above ``queue_high`` for
               ``persistence`` observations, and the world is below
               ``max_np`` — load is arriving faster than it drains.
               Serving mode (ISSUE 19) feeds the SAME persistence
               counter from two more triggers: per-replica request rate
               above ``rate_high`` req/s, or fleet p99 latency above
               ``latency_target_ms``
``scale_in``   the fleet has been idle (zero queued work, no cycle
               progress — or, with ``idle_qps`` set, fleet request rate
               below that floor) for ``idle_s`` seconds and the world
               is above ``min_np``
``hold``       anything else — including the ``cooldown_s`` window
               after every non-hold decision, any observation whose
               trend windows have not filled (nulls never scale), and
               the ISSUE 14 stale-state guard: an evict/scale_in that
               would otherwise fire is REFUSED while the fleet's last
               state-plane commit is older than ``commit_max_age_s``
               (``HOROVOD_COMMIT_MAX_AGE_S``; preemption exempt — the
               hardware is leaving either way)
=============  ======================================================

Hysteresis is everywhere deliberate: trends must PERSIST (the
``persistence`` counter), every action opens a cooldown window, and the
idle timer resets on any sign of progress — a discovery flap or one
transient stall must not thrash the world.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional

HOLD = "hold"
SCALE_OUT = "scale_out"
SCALE_IN = "scale_in"
EVICT = "evict"
PREEMPT = "preempt"


@dataclasses.dataclass(frozen=True)
class ScaleDecision:
    """One typed policy verdict.

    ``action`` is one of ``hold``/``scale_out``/``scale_in``/``evict``/
    ``preempt``; ``target_size`` rides the scale actions, ``evict_rank``
    the evict one, ``hosts`` the preempt one, and ``reason`` carries the
    human-readable attribution the driver logs (and the straggler's
    monitor evidence)."""

    action: str
    reason: str = ""
    target_size: Optional[int] = None
    evict_rank: Optional[int] = None
    hosts: tuple = ()

    @property
    def is_hold(self) -> bool:
        return self.action == HOLD


class ScalePolicy:
    """Hysteresis-damped scaling decisions from monitor summaries.

    All thresholds are constructor knobs (wired from ``HOROVOD_AUTOSCALE_*``
    by the driver — docs/elastic.md); the clock is injected through
    ``observe(now=...)`` so tests are deterministic."""

    def __init__(self, min_np: int, max_np: Optional[int] = None,
                 queue_high: float = 16.0, queue_trend_up: float = 4.0,
                 straggler_factor: float = 3.0, persistence: int = 3,
                 cooldown_s: float = 30.0, idle_s: float = 60.0,
                 scale_step: int = 1, commit_max_age_s: float = 0.0,
                 rate_high: float = 0.0, latency_target_ms: float = 0.0,
                 idle_qps: float = 0.0):
        self.min_np = max(1, int(min_np))
        self.max_np = int(max_np) if max_np else None
        self.queue_high = float(queue_high)
        self.queue_trend_up = float(queue_trend_up)
        self.straggler_factor = max(1.0, float(straggler_factor))
        self.persistence = max(1, int(persistence))
        self.cooldown_s = max(0.0, float(cooldown_s))
        self.idle_s = max(0.0, float(idle_s))
        self.scale_step = max(1, int(scale_step))
        # Stale-state guard (ISSUE 14, HOROVOD_COMMIT_MAX_AGE_S): while
        # the fleet's last state-plane commit is older than this, the
        # policy REFUSES evict and scale_in — shrinking a world whose
        # restore point is stale converts an orderly drain into lost
        # work.  0 = off; a summary with no checkpoint telemetry is
        # unknown, never stale (fleets without the state plane keep the
        # old behavior).  Preemption is exempt: the hardware is going
        # away on the platform's schedule either way.
        self.commit_max_age_s = max(0.0, float(commit_max_age_s))
        # Serving mode (ISSUE 19, HOROVOD_AUTOSCALE_{RATE_HIGH,
        # LATENCY_TARGET_MS,IDLE_QPS}): when the fleet runs the serving
        # plane, the load signals are request rate and tail latency, not
        # training queue depth.  ``rate_high`` is a PER-REPLICA request
        # rate (req/s) above which the fleet scales out;
        # ``latency_target_ms`` a fleet p99 SLO that triggers scale-out
        # when breached; ``idle_qps`` a fleet rate floor below which the
        # idle timer may accrue (serving replicas make no training
        # progress, so the progress-based idle test would drain a busy
        # serving fleet).  All default 0 = off: training-only fleets are
        # byte-for-byte unaffected.
        self.rate_high = max(0.0, float(rate_high))
        self.latency_target_ms = max(0.0, float(latency_target_ms))
        self.idle_qps = max(0.0, float(idle_qps))
        self.stale_holds = 0
        # Hysteresis state.
        self._last_action_ts: Optional[float] = None
        self._up_hits = 0
        self._straggler_rank: Optional[int] = None
        self._straggler_hits = 0
        self._idle_since: Optional[float] = None
        self._last_progress_total: Optional[float] = None
        self.decisions = 0             # observability: non-hold verdicts

    # ------------------------------------------------------------ helpers
    def _acted(self, now: float, decision: ScaleDecision) -> ScaleDecision:
        self._last_action_ts = now
        self._up_hits = 0
        self._straggler_hits = 0
        self._straggler_rank = None
        self._idle_since = None
        self.decisions += 1
        return decision

    def _straggler(self, summary: dict, size: int) -> Optional[tuple]:
        """(rank, evidence) when a persistent straggler gates the fleet."""
        slowest = summary.get("slowest_rank")
        # int-normalize: summaries fetched over HTTP round-trip through
        # JSON, which stringifies the per-rank dict's keys.
        per_rank = {int(r): v for r, v in
                    (summary.get("per_rank_cycle_us") or {}).items()}
        if slowest is not None:
            slowest = int(slowest)
        if slowest is None or len(per_rank) < 2 or size - 1 < self.min_np:
            self._straggler_hits = 0
            self._straggler_rank = None
            return None
        others = sorted(v for r, v in per_rank.items() if r != slowest)
        median = others[len(others) // 2]
        worst = per_rank[slowest]
        if median <= 0 or worst < self.straggler_factor * median:
            self._straggler_hits = 0
            self._straggler_rank = None
            return None
        if slowest == self._straggler_rank:
            self._straggler_hits += 1
        else:
            self._straggler_rank = slowest
            self._straggler_hits = 1
        if self._straggler_hits < self.persistence:
            return None
        evidence = (f"monitor attribution: rank {slowest} slowest for "
                    f"{self._straggler_hits} consecutive observations, "
                    f"cycle {worst:g}us vs peer median {median:g}us "
                    f"({worst / median:.1f}x, threshold "
                    f"{self.straggler_factor:g}x), "
                    f"spread {summary.get('cycle_us_spread')}us")
        return slowest, evidence

    # ------------------------------------------------------------ observe
    def observe(self, summary: dict, size: int,
                now: Optional[float] = None,
                preempt_hosts=()) -> ScaleDecision:
        """One policy step.  ``summary`` is a
        :meth:`RankAggregator.summary` record (possibly fetched over
        HTTP), ``size`` the current world size, ``now`` the injected
        clock (defaults to ``time.monotonic()``), and ``preempt_hosts``
        the discovery source's active preemption notices (ISSUE 12)."""
        if now is None:
            now = time.monotonic()
        size = max(0, int(size))

        # 0. Preemption notices outrank EVERYTHING — including the
        # cooldown window: the platform reclaims the hardware on its own
        # schedule, so holding would just convert an orderly drain into a
        # mid-collective crash.  The decision still OPENS a cooldown (via
        # _acted) so the shrink isn't immediately second-guessed by a
        # queue-depth scale-out.
        if preempt_hosts:
            hosts = tuple(sorted(str(h) for h in preempt_hosts))
            return self._acted(now, ScaleDecision(
                PREEMPT,
                reason=(f"preemption notice for host(s) "
                        f"{', '.join(hosts)} (discovery outranks "
                        f"queue/straggler signals)"),
                hosts=hosts))

        if (self._last_action_ts is not None
                and now - self._last_action_ts < self.cooldown_s):
            return ScaleDecision(HOLD, reason="cooldown")

        # Idle tracking feeds scale-in and resets on ANY progress.  Nulls
        # never scale here either: a summary with NO load telemetry at all
        # (both fields None — exporter up but the aggregation table still
        # empty, e.g. right after a join-epoch flush) is UNKNOWN, not
        # idle — the timer must not accrue toward draining a fleet whose
        # load was never observed.
        queue_depth = summary.get("queue_depth")
        progress_total = summary.get("progress_total")
        rate = summary.get("request_rate")
        p99 = summary.get("latency_p99_ms")
        observed = queue_depth is not None or progress_total is not None
        progressed = (progress_total is not None
                      and progress_total != self._last_progress_total)
        self._last_progress_total = progress_total
        busy = bool(queue_depth) or progressed
        if self.idle_qps > 0 and rate is not None:
            # Serving-idle (ISSUE 19): replicas make no training progress,
            # so idleness is "request rate below the floor", not "no cycle
            # progress" — otherwise a fleet serving at full tilt would
            # look idle and get drained.
            observed = True
            busy = rate >= self.idle_qps or bool(queue_depth)
        if busy or not observed:
            self._idle_since = None
        elif self._idle_since is None:
            self._idle_since = now

        # Stale-state guard (ISSUE 14): evict/scale_in shrink the world,
        # and a shrink is only safe while the restore point is fresh —
        # compute it once, consult it at both shrink decisions below.
        commit_age = summary.get("last_commit_age_s")
        stale = (self.commit_max_age_s > 0 and commit_age is not None
                 and float(commit_age) > self.commit_max_age_s)

        # 1. Persistent straggler → drain-and-evict (attributed).
        straggler = self._straggler(summary, size)
        if straggler is not None:
            rank, evidence = straggler
            if stale:
                self.stale_holds += 1
                return ScaleDecision(HOLD, reason=(
                    f"stale-state guard: fleet commit age {commit_age:g}s"
                    f" > {self.commit_max_age_s:g}s "
                    f"(HOROVOD_COMMIT_MAX_AGE_S) — refusing evict of rank"
                    f" {rank} until the fleet commits"))
            return self._acted(now, ScaleDecision(
                EVICT, reason=f"persistent straggler; {evidence}",
                evict_rank=rank))

        # 2. Load trending up → scale out.  Serving mode (ISSUE 19) adds
        # two more triggers to the same persistence counter: per-replica
        # request rate above ``rate_high``, or fleet p99 latency above
        # ``latency_target_ms`` — both null-safe (nulls never scale).
        trend = summary.get("queue_depth_trend")
        rate_hot = (self.rate_high > 0 and rate is not None and size > 0
                    and rate / size > self.rate_high)
        latency_hot = (self.latency_target_ms > 0 and p99 is not None
                       and p99 > self.latency_target_ms)
        high = ((trend is not None and trend > self.queue_trend_up)
                or (queue_depth is not None
                    and queue_depth > self.queue_high)
                or rate_hot or latency_hot)
        self._up_hits = self._up_hits + 1 if high else 0
        if (self._up_hits >= self.persistence
                and (self.max_np is None or size < self.max_np)):
            target = size + self.scale_step
            if self.max_np is not None:
                target = min(target, self.max_np)
            if rate_hot or latency_hot:
                reason = (f"serving load rising: "
                          f"request_rate={rate} ({size} replicas, "
                          f"per-replica high {self.rate_high:g}/s) "
                          f"p99={p99}ms (target "
                          f"{self.latency_target_ms:g}ms) for "
                          f"{self._up_hits} observations")
            else:
                reason = (f"load rising: queue_depth={queue_depth} "
                          f"trend={trend} for {self._up_hits} observations")
            return self._acted(now, ScaleDecision(
                SCALE_OUT, reason=reason, target_size=target))

        # 3. Idle → scale in (refused while the restore point is stale).
        if (size > self.min_np and self._idle_since is not None
                and now - self._idle_since >= self.idle_s):
            if stale:
                self.stale_holds += 1
                return ScaleDecision(HOLD, reason=(
                    f"stale-state guard: fleet commit age {commit_age:g}s"
                    f" > {self.commit_max_age_s:g}s "
                    f"(HOROVOD_COMMIT_MAX_AGE_S) — refusing scale_in "
                    f"until the fleet commits"))
            return self._acted(now, ScaleDecision(
                SCALE_IN,
                reason=(f"idle for {now - self._idle_since:.0f}s "
                        f"(no queued work, no cycle progress)"),
                target_size=max(self.min_np, size - self.scale_step)))

        return ScaleDecision(HOLD)
