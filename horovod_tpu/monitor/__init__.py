"""Cross-rank telemetry & health subsystem (no jax imports).

The first subsystem that can observe the whole fleet at once
(``docs/monitoring.md``): a per-rank :class:`MetricRegistry` the engine,
scheduler, response cache, in-flight ring and runtime sanitizer publish
into; a low-priority **monitor side-channel** through the coordinator
(``csrc/coordinator.cc`` protocol v3) that periodically ships each rank's
metric snapshot and sanitizer ledger tail to every peer; and export
surfaces — a rank-0 HTTP endpoint (``/metrics`` Prometheus + ``/health``
JSON + ``/snapshot``), a ``python -m horovod_tpu.monitor`` CLI, and a
timeline ``monitor`` counter track.

Enable with ``HOROVOD_MONITOR=1``; ``HOROVOD_MONITOR_PORT`` starts the
rank-0 HTTP exporter; ``HOROVOD_MONITOR_INTERVAL`` sets the reporting
period (seconds, default 5).

This package must stay importable without jax (tier-1 purity guard in
``tests/test_monitor.py``): agents reach the engine only through
duck-typed attributes.
"""

from .registry import (  # noqa: F401
    Counter, Gauge, Histogram, MetricRegistry, DEFAULT_BUCKETS,
)
from .aggregator import RankAggregator  # noqa: F401
from .agent import MonitorAgent  # noqa: F401

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricRegistry", "DEFAULT_BUCKETS",
    "RankAggregator", "MonitorAgent",
]
