"""MonitorAgent: wires the telemetry subsystem into a live runtime.

One agent per initialized process (``hvd.init()`` with ``HOROVOD_MONITOR=1``
— see ``common/basics.py``).  Everything here is duck-typed against the
engine/controller/sanitizer objects and imports no jax, so the agent (and
the whole ``horovod_tpu.monitor`` package) stays importable on the jax-free
fast test tier.

Responsibilities:

- own the per-rank :class:`~.registry.MetricRegistry` and register the
  collectors that refresh it from the engine, scheduler primitives,
  response cache, in-flight ring and sanitizer;
- encode this rank's periodic snapshot for the controller's low-priority
  monitor frames (``monitor_source``) and decode peers' re-broadcast
  snapshots into the :class:`~.aggregator.RankAggregator`
  (``monitor_sink``), flushing the table at join-epoch boundaries;
- version-gated fallback: a v2 server never echoes the monitor section, so
  after a grace window the agent stops attaching frames and logs once —
  local metrics keep working, cross-rank aggregation reports unavailable;
- feed the sanitizer's HVD302 stall reports with the *laggards'* ledger
  tails (``peer_ledger_report``) and the timeline with a ``monitor``
  counter track;
- serve ``/metrics`` + ``/health`` over HTTP on rank 0 when a port is
  configured.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, List, Optional

from .aggregator import RankAggregator
from .registry import MetricRegistry
from ..trace.core import PHASES as _TRACE_PHASES
from ..utils.logging import get_logger

log = get_logger()

# Rounds to keep attaching monitor frames while waiting for the server to
# prove it speaks protocol v3 (echoing the MON1 section).  Generous: the
# very first response already carries the echo on a v3 server.
_PROTO_GRACE_ROUNDS = 64


class MonitorAgent:
    """Cross-rank telemetry agent for one runtime process."""

    def __init__(self, engine=None, controller=None, rank: int = 0,
                 world: int = 1, interval_s: float = 5.0, timeline=None,
                 registry: Optional[MetricRegistry] = None):
        self.rank = int(rank)
        self.world = max(1, int(world))
        self.interval_s = max(0.05, float(interval_s))
        self.registry = registry if registry is not None else MetricRegistry()
        self.aggregator = RankAggregator(self.world)
        self._engine = engine
        self._controller = controller
        self._timeline = timeline
        self._lock = threading.Lock()
        self._last_frame = 0.0            # monotonic; 0 = send immediately
        self._last_self_update = 0.0
        self._proto_warned = False
        self.frames_sent = 0
        self.frames_received = 0
        self._tl_last = 0.0
        self._http = None
        self._stall = None
        self._peer_cb = self.peer_ledger_report    # stable bound-method ref
        if engine is not None:
            self._register_collectors(engine, controller)
            engine.monitor = self
            stall = getattr(engine, "stall", None)
            if stall is not None and hasattr(stall, "peer_ledger_source"):
                # Sanitizer mode: HVD302 reports quote the laggards'
                # ledger tails from the aggregation table.
                stall.peer_ledger_source = self._peer_cb
                self._stall = stall
        # Control-plane fault state (HVD303): set by the engine's
        # _abort_engine hook; flips /health to "peer_dead" with the
        # dead-rank list so operators see WHO died, not just that the
        # fleet degraded.
        self._peer_failure: Optional[dict] = None
        # Readiness latch (ISSUE 19 satellite, docs/serving.md): /ready
        # splits load-balancer admission from liveness.  A draining
        # replica is perfectly HEALTHY (in-flight requests must finish,
        # so /health stays ok) but must take no NEW traffic — the elastic
        # drain path flips this to NotReady the moment the driver's
        # cordon reaches the worker (elastic/worker.py), and the serving
        # front door flips it around its own drain.
        self._ready = True
        self._not_ready_reason = ""
        if controller is not None:
            controller.monitor_source = self.encode_frame
            controller.monitor_sink = self.on_frames
            controller.on_join_epoch = self.on_join_epoch
            # HVD303 attribution: PeerFailureError / RoundTimeoutError
            # messages are enriched with the dead ranks' last snapshot
            # ages and ledger tails from the aggregation table.
            controller.fault_enricher = self.peer_failure_context
            # Clean-LEAVE notices (protocol v6): the departed rank stops
            # counting toward liveness, so /health stays ok — an orderly
            # departure is not a degradation.
            if hasattr(controller, "peer_leave_hook"):
                controller.peer_leave_hook = self.on_peer_leave

    # ----------------------------------------------------------- collectors
    def _register_collectors(self, engine, controller) -> None:
        reg = self.registry
        self.cycle_hist = reg.histogram(
            "hvd_cycle_time_us", "coordinator cycle wall time (us)")

        def collect(reg: MetricRegistry) -> None:
            reg.counter("hvd_cycles_total",
                        "coordinator cycles run").set_total(
                getattr(engine, "cycle_count", 0))
            cyc = max(1, getattr(engine, "cycle_count", 0))
            reg.gauge("hvd_cycle_us_avg",
                      "mean coordinator cycle wall time (us)").set(
                round(getattr(engine, "cycle_us_total", 0.0) / cyc, 2))
            last = getattr(engine, "last_cycle_ts", 0.0)
            reg.gauge("hvd_last_cycle_age_s",
                      "seconds since the last coordinator cycle").set(
                round(time.time() - last, 3) if last else -1)
            reg.counter("hvd_negotiation_us_total",
                        "cumulative negotiation wall time (us)").set_total(
                getattr(engine, "negotiation_us_total", 0.0))
            reg.counter("hvd_negotiation_cycles_total",
                        "negotiation rounds run").set_total(
                getattr(engine, "negotiation_cycles", 0))
            reg.counter("hvd_pipeline_chunks_total",
                        "fused-reduce chunks dispatched").set_total(
                getattr(engine, "pipeline_chunks_total", 0))
            reg.counter("hvd_pipeline_dispatches_total",
                        "fused batches dispatched").set_total(
                getattr(engine, "pipeline_dispatches", 0))
            # FSDP prefetch lane (ISSUE 18): dispatches count allgather
            # batches routed through the PREFETCH lane; overlapped counts
            # the ones issued while an earlier bucket was still unsettled
            # — overlapped/dispatches is the pipelining efficiency the
            # prefetch-depth knob tunes.
            reg.counter("hvd_prefetch_dispatches_total",
                        "prefetch-lane allgather batches dispatched"
                        ).set_total(
                getattr(engine, "prefetch_dispatches", 0))
            reg.counter("hvd_prefetch_overlapped_total",
                        "prefetch allgathers overlapped with compute"
                        ).set_total(
                getattr(engine, "prefetch_overlapped", 0))
            # Two-level allgather legs mirror the allreduce counters:
            # intra legs ride ICI, cross legs ride DCN leaders.
            reg.counter("hvd_hier_ag_dispatches_total",
                        "two-level allgather batches dispatched").set_total(
                getattr(engine, "hier_ag_dispatches", 0))
            reg.counter("hvd_hier_ag_intra_legs_total",
                        "intra-slice allgather legs run").set_total(
                getattr(engine, "hier_ag_intra_legs", 0))
            reg.counter("hvd_hier_ag_cross_legs_total",
                        "cross-slice allgather legs run").set_total(
                getattr(engine, "hier_ag_cross_legs", 0))
            # Two-level broadcast legs (ISSUE 19): cross legs are the
            # root→leader DCN exchange, intra legs the ICI fan-out.
            reg.counter("hvd_hier_bcast_dispatches_total",
                        "two-level broadcast batches dispatched").set_total(
                getattr(engine, "hier_bcast_dispatches", 0))
            reg.counter("hvd_hier_bcast_intra_legs_total",
                        "intra-slice broadcast fan-out legs run").set_total(
                getattr(engine, "hier_bcast_intra_legs", 0))
            reg.counter("hvd_hier_bcast_cross_legs_total",
                        "cross-slice broadcast leader legs run").set_total(
                getattr(engine, "hier_bcast_cross_legs", 0))
            reg.counter("hvd_slice_map_fallbacks_total",
                        "HOROVOD_SLICE_MAP rejections (non-uniform "
                        "slices); hierarchical collectives forced flat"
                        ).set_total(
                getattr(engine, "slice_map_fallbacks", 0))
            queue = getattr(engine, "queue", None)
            if queue is not None:
                reg.gauge("hvd_queue_pending",
                          "entries awaiting negotiation").set(
                    queue.pending_count())
            cache = getattr(engine, "cache", None)
            if cache is not None:
                reg.counter("hvd_program_cache_hits_total",
                            "fused-program cache hits").set_total(cache.hits)
                reg.counter("hvd_program_cache_misses_total",
                            "fused-program cache misses").set_total(
                    cache.misses)
                reg.counter("hvd_program_cache_evictions_total",
                            "fused-program cache evictions").set_total(
                    cache.evictions)
                reg.gauge("hvd_program_cache_size",
                          "compiled fused programs held").set(len(cache))
            ring = getattr(engine, "_inflight", None)
            if ring is not None:
                reg.gauge("hvd_inflight_depth",
                          "dispatched-but-unsettled batches").set(len(ring))
                reg.gauge("hvd_inflight_high_water",
                          "in-flight window high-water mark").set(
                    ring.high_water)
                reg.counter("hvd_inflight_dispatched_total",
                            "batches through the in-flight ring").set_total(
                    ring.dispatched)
            stall = getattr(engine, "stall", None)
            stalled = getattr(stall, "stalled", None)
            if stalled is not None:
                reg.gauge("hvd_stalled_collectives",
                          "collectives past the stall-warn threshold").set(
                    len(stalled))
            san = getattr(engine, "sanitizer", None)
            if san is not None:
                reg.gauge("hvd_sanitizer_ledger_entries",
                          "entries in the sanitizer ledger").set(
                    len(san.ledger))
            sp = getattr(engine, "stateplane", None)
            if sp is not None:
                # Resilient state plane (ISSUE 14): commit freshness is
                # the autoscaler's stale-state guard input, epoch/failure
                # counters the recovery audit trail.
                st = sp.status()
                age = st.get("last_commit_age_s")
                if age is None:
                    # Same sentinel as the aggregator's fleet view: an
                    # armed-but-never-committed rank is effectively
                    # infinitely stale, never "fresher than everyone" —
                    # a -1 here would hide exactly this rank from any
                    # age > threshold alert while the autoscaler guard
                    # is pinning the world size on its account.
                    from .aggregator import NEVER_COMMITTED_AGE_S
                    age = NEVER_COMMITTED_AGE_S
                reg.gauge("hvd_last_commit_age_s",
                          "seconds since the last state-plane commit "
                          "(never committed = 1e12 sentinel)").set(age)
                reg.gauge("hvd_ckpt_epoch",
                          "this rank's in-memory committed epoch").set(
                    st.get("epoch", -1))
                reg.gauge("hvd_ckpt_durable_epoch",
                          "this rank's newest on-disk epoch").set(
                    st.get("durable_epoch", -1))
                reg.counter("hvd_ckpt_write_failures_total",
                            "abandoned checkpoint epochs").set_total(
                    st.get("write_failures", 0))
                reg.counter(
                    "hvd_ckpt_chunks_total",
                    "checkpoint-lane chunk writes dispatched").set_total(
                    getattr(engine, "ckpt_chunks_dispatched", 0))
            tracer = getattr(engine, "tracer", None)
            if tracer is not None:
                # Per-phase lifecycle histograms (horovod_tpu.trace):
                # mirrored from the recorder's own buckets — visible at
                # /metrics as hvd_trace_<phase>_us and in the CLI view.
                # Once the two-level data plane engages, the recorder's
                # payload grows reduce_intra/reduce_cross leg keys
                # (core.REDUCE_LEGS) and the same loop materializes
                # hvd_trace_reduce_intra_us / hvd_trace_reduce_cross_us —
                # the DCN-vs-ICI attribution on /metrics.
                try:
                    hists = tracer.phase_histograms()
                except Exception:  # noqa: BLE001 - telemetry only
                    hists = {}
                for phase, (counts, sum_us, count) in hists.items():
                    reg.histogram(
                        f"hvd_trace_{phase}_us",
                        f"tensor-lifecycle {phase} phase (us)",
                        buckets=tracer.buckets).set_cumulative(
                        counts, sum_us, count)
                reg.counter("hvd_trace_spans_total",
                            "lifecycle spans committed").set_total(
                    tracer.spans_committed)
                reg.counter("hvd_trace_spans_dropped_total",
                            "span claims dropped (ring full)").set_total(
                    tracer.dropped)
            ctl = controller if controller is not None \
                else getattr(engine, "controller", None)
            if ctl is not None:
                st = ctl.cache_stats
                reg.counter("hvd_response_cache_hits_total",
                            "bit-announce cache hits").set_total(st.hits)
                reg.counter("hvd_response_cache_misses_total",
                            "full-announce cache misses").set_total(st.misses)
                reg.counter("hvd_response_cache_invalidations_total",
                            "response-cache slots dropped").set_total(
                    st.invalidations)
                reg.counter("hvd_response_cache_evictions_total",
                            "coordinated evictions seen").set_total(
                    st.evictions)
                reg.counter("hvd_controller_bytes_sent_total",
                            "negotiation request bytes").set_total(
                    ctl.bytes_sent)
                # Zero-RTT warm path (protocol v7): speculation outcomes
                # and the in-flight round window.
                reg.counter("hvd_spec_hits_total",
                            "speculative verdicts validated").set_total(
                    getattr(ctl, "spec_hits", 0))
                reg.counter("hvd_spec_mispredicts_total",
                            "speculative verdicts mispredicted").set_total(
                    getattr(ctl, "spec_mispredicts", 0))
                reg.counter("hvd_spec_rounds_total",
                            "rounds whose verdict skipped the "
                            "response wait").set_total(
                    getattr(ctl, "spec_rounds", 0))
                reg.gauge("hvd_inflight_rounds",
                          "negotiation responses currently unread").set(
                    getattr(ctl, "inflight_rounds", 0))
                reg.gauge("hvd_inflight_rounds_high_water",
                          "in-flight negotiation round high-water").set(
                    getattr(ctl, "inflight_high_water", 0))
                reg.counter("hvd_monitor_frame_bytes_total",
                            "monitor side-channel bytes sent").set_total(
                    getattr(ctl, "monitor_bytes_sent", 0))
            reg.counter("hvd_monitor_frames_sent_total",
                        "monitor snapshots shipped").set_total(
                self.frames_sent)
            reg.counter("hvd_monitor_frames_received_total",
                        "peer snapshots received").set_total(
                self.frames_received)
            reg.counter("hvd_monitor_table_flushes_total",
                        "aggregation-table flushes (join epochs)").set_total(
                self.aggregator.flushes)

        reg.register_collector(collect)

    # ------------------------------------------------------------ snapshots
    def local_snapshot(self) -> dict:
        """This rank's side-channel payload (also the self-entry the
        aggregator keeps fresh in single-controller mode)."""
        eng = self._engine
        snap: dict = {"rank": self.rank, "ts": round(time.time(), 3)}
        if eng is not None:
            cyc = getattr(eng, "cycle_count", 0)
            snap["cycle"] = getattr(eng, "_cycle_index", 0)
            snap["cycle_us_avg"] = (
                round(getattr(eng, "cycle_us_total", 0.0) / cyc, 2)
                if cyc else None)
            last = getattr(eng, "last_cycle_ts", 0.0)
            snap["last_cycle_age_s"] = (
                round(time.time() - last, 3) if last else None)
            stall = getattr(eng, "stall", None)
            stalled = getattr(stall, "stalled", None)
            snap["stalled"] = sorted(stalled) if stalled else []
            san = getattr(eng, "sanitizer", None)
            if san is not None:
                snap["ledger"] = [e.render() for e in san.tail(8)]
            sp = getattr(eng, "stateplane", None)
            if sp is not None:
                # State-plane block (ISSUE 14): rides the side-channel so
                # rank 0's /health can report fleet commit age and the
                # stale-state guard has its input.  Version-safe: peers
                # without the plane just omit the key.
                try:
                    snap["checkpoint"] = sp.status()
                except Exception:  # noqa: BLE001 - telemetry only
                    pass
            tracer = getattr(eng, "tracer", None)
            if tracer is not None:
                # Compact per-cycle phase digest (horovod_tpu.trace):
                # rides the MON1 side-channel inside this JSON blob —
                # size-capped by the recorder (DIGEST_* caps) and version-
                # safe (pre-trace peers ignore unknown snapshot keys).
                try:
                    snap["trace"] = tracer.digest()
                except Exception:  # noqa: BLE001 - telemetry only
                    pass
        snap["metrics"] = self.registry.snapshot()
        return snap

    def _update_self(self, force: bool = False) -> None:
        now = time.monotonic()
        with self._lock:
            if not force and now - self._last_self_update < self.interval_s:
                return
            self._last_self_update = now
        self.aggregator.update(self.rank, self.local_snapshot())

    # ------------------------------------------- controller frame callbacks
    def encode_frame(self) -> Optional[bytes]:
        """``monitor_source`` for the controller: a serialized snapshot
        every ``interval_s``, else None (the round carries no monitor
        bytes).  Runs on the cycle thread inside the negotiation round —
        must be cheap and must NEVER raise (the controller guards it too).
        """
        ctl = self._controller
        if ctl is not None and not ctl.peer_monitor_proto \
                and getattr(ctl, "rounds", 0) > _PROTO_GRACE_ROUNDS:
            # Version-gated fallback: the server never echoed the monitor
            # section — it predates protocol v3.  Stop paying frame bytes;
            # local metrics (and the HTTP exporter's own-rank view) keep
            # working without cross-rank aggregation.
            if not self._proto_warned:
                self._proto_warned = True
                log.warning(
                    "monitor: coordinator does not speak the monitor "
                    "side-channel (protocol < v3); cross-rank aggregation "
                    "disabled, local metrics only")
            return None
        now = time.monotonic()
        with self._lock:
            if now - self._last_frame < self.interval_s:
                return None
            self._last_frame = now
        snap = self.local_snapshot()
        blob = json.dumps(snap, separators=(",", ":")).encode()
        if len(blob) > 48 * 1024:
            # Stay far inside the server's per-blob cap (64KB): a
            # pathological metric/ledger explosion degrades to the core
            # health fields rather than being dropped wholesale.
            snap.pop("metrics", None)
            snap["ledger"] = (snap.get("ledger") or [])[-2:]
            blob = json.dumps(snap, separators=(",", ":")).encode()
            if len(blob) > 64 * 1024:   # still absurd: skip this interval
                return None
        self.frames_sent += 1
        return blob

    def on_frames(self, blobs: List[tuple]) -> None:
        """``monitor_sink``: peers' (and our own, echoed) fresh snapshots
        re-broadcast by the server this round."""
        for rank, blob in blobs:
            try:
                self.aggregator.update(rank, json.loads(blob.decode()))
                self.frames_received += 1
            except (ValueError, UnicodeDecodeError):
                log.warning("monitor: undecodable snapshot from rank %s",
                            rank)
        self._emit_timeline()

    def on_join_epoch(self, last_rank: int = -1) -> None:
        """Join epoch ended: the table's snapshots describe an uneven
        world — flush, like the response-cache slot table."""
        self.aggregator.flush()

    # ------------------------------------------------------------ engine hook
    def on_cycle(self, cycle_us: float) -> None:
        """Per-cycle engine hook (coordinator thread): histogram the cycle
        time; keep the self-entry fresh at the reporting interval so
        ``/health`` works in single-controller mode too."""
        try:
            self.cycle_hist.observe(cycle_us)
            if self._controller is None:
                self._update_self()
                self._emit_timeline()
        except Exception:  # noqa: BLE001 - telemetry must never cost a cycle
            pass

    def _emit_timeline(self) -> None:
        tl = self._timeline
        if tl is None or not getattr(tl, "enabled", False):
            return
        now = time.monotonic()
        if now - self._tl_last < self.interval_s:
            return
        self._tl_last = now
        skew = self.aggregator.skew()
        ctl = self._controller
        tl.counter("monitor", {
            "ranks_reporting": len(self.aggregator.ranks()),
            "cycle_us_spread": skew.get("cycle_us_spread") or 0,
            "monitor_bytes":
                getattr(ctl, "monitor_bytes_sent", 0) if ctl else 0})

    # ------------------------------------------------------- fault hooks
    def on_peer_leave(self, ranks) -> None:
        """Controller hook (protocol v6 leave notice): clean departures —
        marked in the aggregator so liveness accounting skips them;
        deliberately NOT a fault latch (``/health`` stays ok)."""
        for r in ranks or []:
            self.aggregator.mark_left(int(r))

    def on_peer_failure(self, dead_ranks, reason: str = "") -> None:
        """Engine hook (``_abort_engine``): latch the control-plane fault
        so ``/health`` reports ``peer_dead`` with attribution."""
        self._peer_failure = {
            "dead_ranks": sorted(int(r) for r in (dead_ranks or [])),
            "reason": str(reason)[:2000],
            "ts": round(time.time(), 3),
        }

    def peer_failure_context(self, dead_ranks=None) -> str:
        """Attribution block for HVD303 errors: the dead ranks' last
        snapshot ages and ledger tails from the aggregation table (or, for
        unattributed round timeouts, every rank's snapshot age — the
        stalest rank is the prime suspect)."""
        table = self.aggregator.table()
        if not table:
            return ""
        ranks = (sorted(int(r) for r in dead_ranks)
                 if dead_ranks else sorted(table))
        lines = []
        for r in ranks:
            rec = table.get(r)
            if rec is None:
                lines.append(f"rank {r}: no snapshot ever received")
                continue
            lines.append(f"rank {r}: last snapshot {rec['age_s']:g}s ago")
            for t in (rec["snap"].get("ledger") or [])[-4:]:
                lines.append(f"  {t}")
        if not lines:
            return ""
        return ("monitor attribution (snapshot ages via side-channel):\n"
                + "\n".join(lines))

    # --------------------------------------------------------- readiness
    def set_ready(self, ready: bool, reason: str = "") -> None:
        """Flip the /ready verdict.  Liveness is DERIVED (snapshot ages,
        stall state); readiness is DECLARED — cordon/drain and serving
        front-door state own it, so a load balancer stops routing to a
        draining replica while /health still reads ok."""
        self._ready = bool(ready)
        self._not_ready_reason = "" if ready else str(reason)[:500]

    def readiness(self) -> dict:
        """The ``/ready`` JSON body: the declared latch AND the derived
        fault state — a rank whose control plane died is not ready either,
        whatever the latch says."""
        pf = self._peer_failure
        if pf is not None:
            return {"ready": False,
                    "reason": f"peer_dead: {pf['reason'] or pf['dead_ranks']}"}
        return {"ready": self._ready,
                "reason": self._not_ready_reason if not self._ready else ""}

    # -------------------------------------------------------------- exports
    def health(self) -> dict:
        self._update_self(force=True)
        out = self.aggregator.health(self.interval_s)
        out["ready"] = self.readiness()["ready"]
        pf = self._peer_failure
        if pf is not None:
            # A declared control-plane fault outranks every derived
            # status: the fleet is not "degraded", it lost a member.
            out["status"] = "peer_dead"
            out["peer_dead"] = pf["dead_ranks"]
            out["peer_dead_reason"] = pf["reason"]
        return out

    def render_prometheus(self) -> str:
        self._update_self(force=True)
        out = [self.registry.to_prometheus(f'rank="{self.rank}"')]
        # Aggregated per-rank series from the side-channel table.
        table = self.aggregator.table()
        if table:
            out.append("# TYPE hvd_rank_alive gauge")
            for r in sorted(table):
                alive = self.aggregator.is_alive(table[r]["age_s"],
                                                 self.interval_s)
                out.append(f'hvd_rank_alive{{rank="{r}"}} {1 if alive else 0}')
            out.append("# TYPE hvd_rank_cycle_us_avg gauge")
            for r in sorted(table):
                v = table[r]["snap"].get("cycle_us_avg")
                if v is not None:
                    out.append(f'hvd_rank_cycle_us_avg{{rank="{r}"}} {v:g}')
            out.append("# TYPE hvd_rank_stalled_collectives gauge")
            for r in sorted(table):
                n = len(table[r]["snap"].get("stalled") or [])
                out.append(
                    f'hvd_rank_stalled_collectives{{rank="{r}"}} {n}')
        # Windowed trend gauges (autoscale policy inputs): emitted only
        # once their EWMA window fills — absence IS the null.
        summary = self.aggregator.summary()
        for name in ("cycle_us_spread_trend", "queue_depth_trend",
                     "request_rate", "request_rate_trend",
                     "latency_p99_ms"):
            v = summary.get(name)
            if v is not None:
                out.append(f"# TYPE hvd_{name} gauge")
                out.append(f"hvd_{name} {v:g}")
        return "\n".join(out) + "\n"

    def dump(self) -> dict:
        """Raw JSON snapshot (``/snapshot``; the CLI pretty-prints it)."""
        self._update_self(force=True)
        return {"rank": self.rank, "world": self.world,
                "health": self.aggregator.health(self.interval_s),
                "table": {str(r): rec["snap"]
                          for r, rec in self.aggregator.table().items()}}

    def peer_ledger_report(self) -> str:
        """Laggard attribution block for HVD302 stall reports: every peer
        rank's last submissions from the aggregation table, plus — when
        the peers run with tracing armed — the phase each laggard is
        currently stuck in and its last completed cycle's phase breakdown
        (the trace digest that rode the same side-channel)."""
        tails = self.aggregator.peer_ledger_tails(exclude_rank=self.rank)
        table = self.aggregator.table()

        def _has_trace(rec):
            tr = rec["snap"].get("trace") or {}
            return tr.get("open") or tr.get("cycles")

        if not tails and not any(_has_trace(rec) for r, rec in table.items()
                                 if r != self.rank):
            return ""
        lines = []
        ranks = set(tails) | {r for r in table if r != self.rank}
        for r in sorted(ranks):
            if r in tails:
                lines.append(f"rank {r} last submissions:")
                lines.extend(f"  {t}" for t in tails[r])
            lines.extend(f"  {t}" for t in self._peer_phase_lines(table, r))
        return "peer ledgers (via monitor side-channel):\n" + \
            "\n".join(lines)

    @staticmethod
    def _peer_phase_lines(table: dict, rank: int) -> List[str]:
        """Trace-digest attribution for one peer: current phase per open
        span, and the last completed cycle's per-phase microseconds."""
        rec = table.get(rank)
        tr = (rec["snap"].get("trace") or {}) if rec else {}
        lines: List[str] = []
        for name, phase in sorted((tr.get("open") or {}).items()):
            lines.append(f"rank {rank} currently in phase {phase}: {name}")
        cycles = tr.get("cycles") or []
        if cycles:
            row = cycles[-1]
            # [cycle, n_tensors, queue, negotiation, copy_in, reduce, drain]
            body = "  ".join(f"{p}={v}us"
                             for p, v in zip(_TRACE_PHASES, row[2:]))
            lines.append(f"rank {rank} last cycle {row[0]} "
                         f"({row[1]} tensors): {body}")
        return lines

    # ------------------------------------------------------------ lifecycle
    def serve_http(self, port: int, addr: str = ""):
        from .http import MonitorHTTPServer
        self._http = MonitorHTTPServer(self, port=port, addr=addr).start()
        return self._http

    @property
    def http_port(self) -> Optional[int]:
        return self._http.port if self._http is not None else None

    def close(self) -> None:
        if self._http is not None:
            self._http.stop()
            self._http = None
        ctl = self._controller
        if ctl is not None:
            # The agent owns the controller hooks it installed.
            ctl.monitor_source = None
            ctl.monitor_sink = None
            ctl.on_join_epoch = None
            # Like the stall source below: only uninstall OUR enricher —
            # a replacement agent may have installed its own.
            if getattr(ctl, "fault_enricher", None) is not None and \
                    getattr(ctl.fault_enricher, "__self__", None) is self:
                ctl.fault_enricher = None
        if self._stall is not None:
            # A replacement agent may have re-installed its own source
            # (e.g. the bench A/B attaches a temporary agent to a live
            # engine): only uninstall OUR callback, never someone else's.
            if getattr(self._stall, "peer_ledger_source", None) \
                    is self._peer_cb:
                self._stall.peer_ledger_source = None
            self._stall = None
        eng = self._engine
        if eng is not None and getattr(eng, "monitor", None) is self:
            eng.monitor = None
