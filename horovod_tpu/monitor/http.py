"""Opt-in HTTP export surface for the telemetry subsystem (no jax imports).

Runs on rank 0 when ``HOROVOD_MONITOR_PORT`` is set (``docs/monitoring.md``):

- ``GET /metrics`` — Prometheus text format: this rank's registry plus
  per-rank aggregated series (``hvd_rank_*{rank="r"}``) derived from the
  controller side-channel's aggregation table.
- ``GET /health``  — JSON: fleet status (``ok``/``stalled``/``degraded``),
  per-rank liveness, last-cycle age and stall state, slowest-rank /
  cycle-time-spread attribution.
- ``GET /ready``   — readiness split from liveness (ISSUE 19): 200 while
  this replica accepts new work, 503 (with a JSON reason) during
  cordon/drain — the load balancer's routing signal, distinct from
  ``/health``'s stall-driven 503.
- ``GET /snapshot`` — raw JSON dump of the aggregation table (the format
  ``python -m horovod_tpu.monitor <file>`` pretty-prints).

Stdlib ``ThreadingHTTPServer`` on a daemon thread: scrapes never touch the
coordinator cycle thread — they read lock-guarded tables only.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from ..utils.logging import get_logger

log = get_logger()


class MonitorHTTPServer:
    """Serve ``/metrics`` + ``/health`` + ``/ready`` + ``/snapshot`` for a
    MonitorAgent."""

    def __init__(self, agent, port: int = 0, addr: str = ""):
        self._agent = agent
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # silence stdlib request logging
                pass

            def _send(self, code: int, ctype: str, body: str):
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):  # noqa: N802 - stdlib API
                try:
                    path = self.path.split("?", 1)[0]
                    if path == "/metrics":
                        self._send(200, "text/plain; version=0.0.4",
                                   outer._agent.render_prometheus())
                    elif path == "/health":
                        health = outer._agent.health()
                        code = 200 if health.get("status") == "ok" else 503
                        self._send(code, "application/json",
                                   json.dumps(health, indent=2))
                    elif path == "/ready":
                        # Readiness vs liveness (ISSUE 19): the LB's
                        # routing signal.  NotReady during cordon/drain
                        # while /health keeps reporting the truthful
                        # liveness picture — a draining replica is
                        # healthy, just not accepting new work.
                        ready = outer._agent.readiness()
                        code = 200 if ready.get("ready") else 503
                        self._send(code, "application/json",
                                   json.dumps(ready, indent=2))
                    elif path == "/snapshot":
                        self._send(200, "application/json",
                                   json.dumps(outer._agent.dump(), indent=2))
                    else:
                        self._send(404, "text/plain",
                                   "try /metrics, /health, /ready or "
                                   "/snapshot\n")
                except BrokenPipeError:  # pragma: no cover - client gone
                    pass
                except Exception as exc:  # noqa: BLE001 - keep serving
                    try:
                        self._send(500, "text/plain", f"{exc}\n")
                    except Exception:  # pragma: no cover
                        pass

        self._httpd = ThreadingHTTPServer((addr, int(port)), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "MonitorHTTPServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="hvd-tpu-monitor-http",
            daemon=True)
        self._thread.start()
        log.info("monitor: HTTP exporter listening on :%d "
                 "(/metrics, /health, /snapshot)", self.port)
        return self

    def stop(self) -> None:
        try:
            # shutdown() BLOCKS until serve_forever exits — only safe when
            # start() actually ran; a never-started server just closes.
            if self._thread is not None:
                self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:  # noqa: BLE001 - already down
            pass
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
