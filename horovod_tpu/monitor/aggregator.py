"""Rank-0 aggregation table for cross-rank telemetry (no jax imports).

Every rank periodically ships a snapshot blob (metrics + sanitizer ledger
tail + stall state) through the coordinator's low-priority monitor frames
(``csrc/coordinator.cc`` protocol v3, ``common/controller.py``); the server
re-broadcasts fresh blobs to every rank, so each process — most usefully
rank 0, which serves ``/metrics`` and ``/health`` — holds the same
fleet-wide table.

What the table answers that no per-rank view can:

- **skew / straggler attribution**: slowest rank id and the cycle-time
  spread across the fleet (the Horovod paper's "one slow rank gates the
  world" diagnosis, computed instead of guessed);
- **laggard ledger tails**: a stalling rank's HVD302 report can quote the
  *laggard's* last submissions (the ROADMAP ledger-exchange item) — see
  ``analysis/runtime_sanitizer.py``;
- **liveness**: a rank whose snapshots stopped arriving is dead or wedged
  even while the lock-step protocol technically still waits on it.

A join epoch flushes the table (``controller.on_join_epoch``): snapshots
captured while the world was uneven must not survive into the resumed
world (mirrors the response-cache slot flush at the same boundary).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional


# Fleet commit age reported for a rank whose state plane is armed but
# has never committed (ISSUE 14): effectively-infinitely stale, but a
# FINITE float — float('inf') would serialize into /health as the
# non-standard JSON token `Infinity` and break strict parsers (jq,
# JSON.parse, Go) exactly when operators look during startup/rejoin.
NEVER_COMMITTED_AGE_S = 1e12


class EwmaTrend:
    """Windowed EWMA trend of a scalar series: fast EWMA minus slow EWMA.

    Positive = the series is rising, negative = falling, ~0 = flat; the
    magnitude is in the series' own units, so thresholds stay intuitive
    (a ``queue_depth_trend`` of 3 means the backlog is ~3 entries above
    its recent baseline).  ``trend`` is ``None`` until ``min_samples``
    observations arrived — the autoscale policy treats nulls as
    "window not filled, hold" — and ``reset()`` re-empties the window
    (join-epoch flush: samples from an uneven world must not steer
    scaling decisions into the resumed one)."""

    def __init__(self, fast: float = 0.5, slow: float = 0.1,
                 min_samples: int = 5):
        self.fast_alpha = float(fast)
        self.slow_alpha = float(slow)
        self.min_samples = max(1, int(min_samples))
        self._fast: Optional[float] = None
        self._slow: Optional[float] = None
        self._n = 0

    def update(self, value: float) -> None:
        v = float(value)
        self._fast = v if self._fast is None else (
            self.fast_alpha * v + (1 - self.fast_alpha) * self._fast)
        self._slow = v if self._slow is None else (
            self.slow_alpha * v + (1 - self.slow_alpha) * self._slow)
        self._n += 1

    @property
    def trend(self) -> Optional[float]:
        if self._n < self.min_samples:
            return None
        return round(self._fast - self._slow, 4)

    @property
    def level(self) -> Optional[float]:
        """Smoothed current value (the fast EWMA), null until the window
        fills — the serving summary's ``request_rate``/``latency_p99_ms``
        read this so one noisy sample never steers a scale decision."""
        if self._n < self.min_samples:
            return None
        return round(self._fast, 4)

    def reset(self) -> None:
        self._fast = None
        self._slow = None
        self._n = 0


def merged_percentile(hists, q: float) -> Optional[float]:
    """Percentile of the UNION of per-rank histogram snapshots (the
    ``{"count", "sum", "buckets": {le: cum}}`` shape the registry ships
    over the side-channel).  Buckets merge by upper bound — every rank
    publishes the same serving-latency buckets, so the cumulative counts
    add directly; interpolation inside the crossing bucket matches
    ``registry.Histogram.percentile``.  None until anything observed.

    The empty contract is AUDITED to match the local path exactly
    (ISSUE 20: the front door's hedging delay reads a p99 at startup,
    before any traffic, through either path): no snapshots, all-empty
    snapshots, and count-without-finite-buckets snapshots all return
    ``None`` here and from ``Histogram.percentile`` alike — never 0.0,
    never a crash."""
    merged: Dict[float, int] = {}
    total = 0
    for h in hists:
        if not h:
            continue
        total += int(h.get("count") or 0)
        for le, cum in (h.get("buckets") or {}).items():
            le = float(le)
            merged[le] = merged.get(le, 0) + int(cum)
    if total == 0 or not merged:
        return None
    target = q * total
    lo = 0.0
    prev_cum = 0
    for le in sorted(merged):
        cum = merged[le]
        if cum > prev_cum and cum >= target:
            frac = (target - prev_cum) / (cum - prev_cum)
            return round(lo + (le - lo) * frac, 4)
        prev_cum = max(prev_cum, cum)
        lo = le
    return max(merged)


class RankAggregator:
    """Per-rank snapshot table + fleet-level derived views."""

    def __init__(self, world: int):
        self.world = max(1, int(world))
        self._lock = threading.Lock()
        # rank -> {"snap": dict, "received_at": monotonic}
        self._table: Dict[int, dict] = {}
        # Ranks that departed via clean LEAVE (protocol v6): excluded from
        # liveness/degraded accounting — an orderly departure must not
        # flip /health — and reported under "left_ranks".  NOT cleared by
        # flush(): the departure outlives any join epoch; only a new
        # controller generation (fresh aggregator) forgets it.
        self._left: set = set()
        # Windowed trend gauges (autoscale policy inputs — docs/elastic.md
        # "Closed-loop autoscaling"): nulls until the window fills,
        # flushed on join epoch like the rest of the table.
        self._spread_trend = EwmaTrend()
        self._queue_trend = EwmaTrend()
        # Serving instruments (ISSUE 19, docs/serving.md): fleet request
        # rate from the summed per-rank request counters differenced at
        # snapshot cadence, and fleet p99 latency from the merged serving
        # histograms — both EWMA-smoothed, nulls until the window fills.
        self._rate_trend = EwmaTrend(min_samples=3)
        self._latency_trend = EwmaTrend(min_samples=3)
        self._serve_last: Optional[tuple] = None   # (requests_total, mono)
        self.flushes = 0
        self.updates = 0

    # ------------------------------------------------------------- writing
    def update(self, rank: int, snap: dict) -> None:
        with self._lock:
            self._table[int(rank)] = {"snap": snap,
                                      "received_at": time.monotonic()}
            self.updates += 1
            # Feed the trend windows at snapshot cadence: spread needs two
            # reporting ranks; queue depth sums every rank's pending count.
            per_rank = [rec["snap"].get("cycle_us_avg")
                        for r, rec in self._table.items()
                        if r not in self._left
                        and rec["snap"].get("cycle_us_avg") is not None]
            if len(per_rank) >= 2:
                self._spread_trend.update(max(per_rank) - min(per_rank))
            q = self._queue_depth_locked()
            if q is not None:
                self._queue_trend.update(q)
            self._update_serving_locked()

    def _update_serving_locked(self) -> None:
        """Feed the serving trends at snapshot cadence: the fleet request
        counter's first derivative (offered QPS) and the merged-histogram
        p99.  No serving metrics reported → no samples → the summary
        fields stay null and the policy's serving mode stays inert."""
        totals = []
        hists = []
        for r, rec in self._table.items():
            if r in self._left:
                continue
            m = rec["snap"].get("metrics") or {}
            v = m.get("hvd_serve_requests_total")
            if v is not None:
                totals.append(float(v))
            h = m.get("hvd_serve_latency_ms")
            if isinstance(h, dict):
                hists.append(h)
        if totals:
            total = sum(totals)
            now = time.monotonic()
            if self._serve_last is not None:
                last_total, last_t = self._serve_last
                dt = now - last_t
                if dt > 1e-3:
                    self._rate_trend.update(
                        max(0.0, total - last_total) / dt)
                    self._serve_last = (total, now)
            else:
                self._serve_last = (total, now)
        p99 = merged_percentile(hists, 0.99)
        if p99 is not None:
            self._latency_trend.update(p99)

    def mark_left(self, rank: int) -> None:
        """Record a clean departure (protocol v6 leave notice): the rank
        stops counting toward liveness — ``/health`` stays ok — and its
        stale snapshot is dropped."""
        with self._lock:
            self._left.add(int(rank))
            self._table.pop(int(rank), None)

    def flush(self) -> None:
        """Drop every snapshot (join-epoch boundary / elastic re-init).
        Trend windows flush with the table; clean-leave records persist
        (the departed rank is still gone in the resumed world)."""
        with self._lock:
            self._table.clear()
            self._spread_trend.reset()
            self._queue_trend.reset()
            self._rate_trend.reset()
            self._latency_trend.reset()
            self._serve_last = None
            self.flushes += 1

    @staticmethod
    def is_alive(age_s: float, interval_s: float) -> bool:
        """THE liveness rule, shared by /health and the /metrics
        ``hvd_rank_alive`` series: a rank is alive while its last snapshot
        is younger than three reporting intervals."""
        return age_s <= max(1.0, 3.0 * interval_s)

    # ------------------------------------------------------------- reading
    def ranks(self) -> List[int]:
        with self._lock:
            return sorted(self._table)

    def snapshot_of(self, rank: int) -> Optional[dict]:
        with self._lock:
            rec = self._table.get(int(rank))
            return rec["snap"] if rec else None

    def table(self) -> Dict[int, dict]:
        """``rank -> {"snap": ..., "age_s": ...}`` copy for exporters."""
        now = time.monotonic()
        with self._lock:
            return {r: {"snap": rec["snap"],
                        "age_s": round(now - rec["received_at"], 3)}
                    for r, rec in self._table.items()}

    def left_ranks(self) -> List[int]:
        with self._lock:
            return sorted(self._left)

    def _queue_depth_locked(self) -> Optional[int]:
        """Fleet queue depth: sum of every reporting rank's
        ``hvd_queue_pending`` gauge; None until someone reports it."""
        vals = []
        for r, rec in self._table.items():
            if r in self._left:
                continue
            v = (rec["snap"].get("metrics") or {}).get("hvd_queue_pending")
            if v is not None:
                vals.append(int(v))
        return sum(vals) if vals else None

    def skew(self) -> dict:
        """Straggler attribution from per-rank cycle timings.

        Each snapshot carries ``cycle_us_avg`` (mean coordinator-cycle
        wall microseconds on that rank).  Returns the slowest rank id and
        the max-min spread; nulls until at least two ranks reported."""
        with self._lock:
            per_rank = {r: rec["snap"].get("cycle_us_avg")
                        for r, rec in self._table.items()
                        if r not in self._left
                        and rec["snap"].get("cycle_us_avg") is not None}
        if len(per_rank) < 2:
            return {"slowest_rank": None, "cycle_us_spread": None,
                    "per_rank_cycle_us": per_rank or None}
        slowest = max(per_rank, key=lambda r: per_rank[r])
        spread = round(max(per_rank.values()) - min(per_rank.values()), 2)
        return {"slowest_rank": slowest, "cycle_us_spread": spread,
                "per_rank_cycle_us": per_rank}

    def summary(self) -> dict:
        """The autoscale policy's observation record (docs/elastic.md):
        straggler attribution plus the windowed trend gauges and fleet
        load figures, so policy inputs are observable standalone — the
        same numbers ride ``/health`` and ``/metrics``.  Trend fields are
        null until their EWMA window fills."""
        out = self.skew()
        with self._lock:
            out["queue_depth"] = self._queue_depth_locked()
            out["cycle_us_spread_trend"] = self._spread_trend.trend
            out["queue_depth_trend"] = self._queue_trend.trend
            # Serving instruments (ISSUE 19): fleet offered QPS (EWMA
            # level of the summed request-counter derivative), its trend
            # (the policy's "offered load rising" input), and fleet p99
            # serving latency — nulls-until-filled like the queue trends,
            # and null forever on fleets that never serve.
            out["request_rate"] = self._rate_trend.level
            out["request_rate_trend"] = self._rate_trend.trend
            out["latency_p99_ms"] = self._latency_trend.level
            out["ranks_reporting"] = len(
                [r for r in self._table if r not in self._left])
            out["left_ranks"] = sorted(self._left)
            # Fleet WORK-progress counter (the autoscale idle detector's
            # input): dispatched batches, NOT coordinator cycles — the
            # engine's cycle index advances on idle ticks too, so an idle
            # fleet would never read as idle through it.  Falls back to
            # the cycle counter for snapshot sources without the dispatch
            # metric.
            prog = []
            for r, rec in self._table.items():
                if r in self._left:
                    continue
                m = rec["snap"].get("metrics") or {}
                v = m.get("hvd_pipeline_dispatches_total")
                if v is None:
                    v = rec["snap"].get("cycle")
                if v is not None:
                    prog.append(v)
            out["progress_total"] = sum(prog) if prog else None
            # Fleet commit age (ISSUE 14, the autoscaler's stale-state
            # guard input): the STALEST reporting rank's state-plane
            # commit age — one rank with an old restore point makes the
            # whole fleet's shrink unsafe.  A rank whose plane is ARMED
            # but has never committed counts as effectively-infinitely
            # stale (NEVER_COMMITTED_AGE_S — finite, so /health stays
            # strict JSON), not invisible: scaling in before its first
            # commit is exactly the lost-work case the guard refuses.
            # Null only when NO rank reports a checkpoint block at all
            # (state plane not armed: guard stays off).
            ages = []
            for r, rec in self._table.items():
                if r in self._left:
                    continue
                ck = rec["snap"].get("checkpoint")
                if ck is None:
                    continue
                age = ck.get("last_commit_age_s")
                ages.append(NEVER_COMMITTED_AGE_S if age is None
                            else float(age))
            out["last_commit_age_s"] = (round(max(ages), 3) if ages
                                        else None)
        return out

    def peer_ledger_tails(self,
                          exclude_rank: Optional[int] = None
                          ) -> Dict[int, List[str]]:
        """rank -> rendered ledger-tail lines, for HVD302 enrichment."""
        out: Dict[int, List[str]] = {}
        with self._lock:
            for r, rec in self._table.items():
                if exclude_rank is not None and r == exclude_rank:
                    continue
                tail = rec["snap"].get("ledger") or []
                if tail:
                    out[r] = list(tail)
        return out

    def health(self, interval_s: float = 5.0) -> dict:
        """The ``/health`` JSON body: per-rank liveness, last-cycle age,
        stall state, plus fleet status and straggler attribution.

        A rank is *alive* while its last snapshot is younger than three
        reporting intervals.  Status: ``stalled`` when any rank reports a
        stalled collective, ``degraded`` when a rank is missing or its
        snapshots aged out, else ``ok``."""
        now = time.monotonic()
        ranks: Dict[str, dict] = {}
        any_stalled = False
        missing = 0
        with self._lock:
            table = dict(self._table)
            left = set(self._left)
        for r in range(self.world):
            if r in left:
                # Clean departure (protocol v6): the rank is GONE by
                # design, not degraded — reported separately, never as
                # missing.
                ranks[str(r)] = {"alive": False, "left": True,
                                 "last_seen_s": None, "cycle": None,
                                 "last_cycle_age_s": None, "stalled": []}
                continue
            rec = table.get(r)
            if rec is None:
                ranks[str(r)] = {"alive": False, "last_seen_s": None,
                                 "cycle": None, "last_cycle_age_s": None,
                                 "stalled": []}
                missing += 1
                continue
            snap = rec["snap"]
            age = now - rec["received_at"]
            alive = self.is_alive(age, interval_s)
            stalled = list(snap.get("stalled") or [])
            any_stalled = any_stalled or bool(stalled)
            missing += 0 if alive else 1
            ranks[str(r)] = {
                "alive": alive,
                "last_seen_s": round(age, 3),
                "cycle": snap.get("cycle"),
                "last_cycle_age_s": snap.get("last_cycle_age_s"),
                "stalled": stalled,
            }
        status = ("stalled" if any_stalled
                  else "degraded" if missing else "ok")
        out = {"status": status, "world": self.world,
               "monitor_interval_s": interval_s, "ranks": ranks}
        out.update(self.summary())
        # Checkpoint block (ISSUE 14): the state plane's fleet view — the
        # per-rank epochs an operator reads to see WHO lags, plus the
        # fleet commit age the stale-state guard consumes (also mirrored
        # flat in the summary above).  Present only when some rank runs
        # the plane.
        ck_ranks = {}
        for r, rec in table.items():
            if r in left:
                continue
            ck = rec["snap"].get("checkpoint")
            if ck:
                ck_ranks[str(r)] = {
                    "epoch": ck.get("epoch"),
                    "durable_epoch": ck.get("durable_epoch"),
                    "last_commit_age_s": ck.get("last_commit_age_s"),
                    "write_failures": ck.get("write_failures"),
                    "last_restore_source": ck.get("last_restore_source"),
                }
        if ck_ranks:
            out["checkpoint"] = {
                "last_commit_age_s": out.get("last_commit_age_s"),
                "min_durable_epoch": min(
                    (v["durable_epoch"] for v in ck_ranks.values()
                     if v["durable_epoch"] is not None), default=None),
                "ranks": ck_ranks,
            }
        return out
