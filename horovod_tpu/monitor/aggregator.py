"""Rank-0 aggregation table for cross-rank telemetry (no jax imports).

Every rank periodically ships a snapshot blob (metrics + sanitizer ledger
tail + stall state) through the coordinator's low-priority monitor frames
(``csrc/coordinator.cc`` protocol v3, ``common/controller.py``); the server
re-broadcasts fresh blobs to every rank, so each process — most usefully
rank 0, which serves ``/metrics`` and ``/health`` — holds the same
fleet-wide table.

What the table answers that no per-rank view can:

- **skew / straggler attribution**: slowest rank id and the cycle-time
  spread across the fleet (the Horovod paper's "one slow rank gates the
  world" diagnosis, computed instead of guessed);
- **laggard ledger tails**: a stalling rank's HVD302 report can quote the
  *laggard's* last submissions (the ROADMAP ledger-exchange item) — see
  ``analysis/runtime_sanitizer.py``;
- **liveness**: a rank whose snapshots stopped arriving is dead or wedged
  even while the lock-step protocol technically still waits on it.

A join epoch flushes the table (``controller.on_join_epoch``): snapshots
captured while the world was uneven must not survive into the resumed
world (mirrors the response-cache slot flush at the same boundary).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional


class RankAggregator:
    """Per-rank snapshot table + fleet-level derived views."""

    def __init__(self, world: int):
        self.world = max(1, int(world))
        self._lock = threading.Lock()
        # rank -> {"snap": dict, "received_at": monotonic}
        self._table: Dict[int, dict] = {}
        self.flushes = 0
        self.updates = 0

    # ------------------------------------------------------------- writing
    def update(self, rank: int, snap: dict) -> None:
        with self._lock:
            self._table[int(rank)] = {"snap": snap,
                                      "received_at": time.monotonic()}
            self.updates += 1

    def flush(self) -> None:
        """Drop every snapshot (join-epoch boundary / elastic re-init)."""
        with self._lock:
            self._table.clear()
            self.flushes += 1

    @staticmethod
    def is_alive(age_s: float, interval_s: float) -> bool:
        """THE liveness rule, shared by /health and the /metrics
        ``hvd_rank_alive`` series: a rank is alive while its last snapshot
        is younger than three reporting intervals."""
        return age_s <= max(1.0, 3.0 * interval_s)

    # ------------------------------------------------------------- reading
    def ranks(self) -> List[int]:
        with self._lock:
            return sorted(self._table)

    def snapshot_of(self, rank: int) -> Optional[dict]:
        with self._lock:
            rec = self._table.get(int(rank))
            return rec["snap"] if rec else None

    def table(self) -> Dict[int, dict]:
        """``rank -> {"snap": ..., "age_s": ...}`` copy for exporters."""
        now = time.monotonic()
        with self._lock:
            return {r: {"snap": rec["snap"],
                        "age_s": round(now - rec["received_at"], 3)}
                    for r, rec in self._table.items()}

    def skew(self) -> dict:
        """Straggler attribution from per-rank cycle timings.

        Each snapshot carries ``cycle_us_avg`` (mean coordinator-cycle
        wall microseconds on that rank).  Returns the slowest rank id and
        the max-min spread; nulls until at least two ranks reported."""
        with self._lock:
            per_rank = {r: rec["snap"].get("cycle_us_avg")
                        for r, rec in self._table.items()
                        if rec["snap"].get("cycle_us_avg") is not None}
        if len(per_rank) < 2:
            return {"slowest_rank": None, "cycle_us_spread": None,
                    "per_rank_cycle_us": per_rank or None}
        slowest = max(per_rank, key=lambda r: per_rank[r])
        spread = round(max(per_rank.values()) - min(per_rank.values()), 2)
        return {"slowest_rank": slowest, "cycle_us_spread": spread,
                "per_rank_cycle_us": per_rank}

    def peer_ledger_tails(self,
                          exclude_rank: Optional[int] = None
                          ) -> Dict[int, List[str]]:
        """rank -> rendered ledger-tail lines, for HVD302 enrichment."""
        out: Dict[int, List[str]] = {}
        with self._lock:
            for r, rec in self._table.items():
                if exclude_rank is not None and r == exclude_rank:
                    continue
                tail = rec["snap"].get("ledger") or []
                if tail:
                    out[r] = list(tail)
        return out

    def health(self, interval_s: float = 5.0) -> dict:
        """The ``/health`` JSON body: per-rank liveness, last-cycle age,
        stall state, plus fleet status and straggler attribution.

        A rank is *alive* while its last snapshot is younger than three
        reporting intervals.  Status: ``stalled`` when any rank reports a
        stalled collective, ``degraded`` when a rank is missing or its
        snapshots aged out, else ``ok``."""
        now = time.monotonic()
        ranks: Dict[str, dict] = {}
        any_stalled = False
        missing = 0
        with self._lock:
            table = dict(self._table)
        for r in range(self.world):
            rec = table.get(r)
            if rec is None:
                ranks[str(r)] = {"alive": False, "last_seen_s": None,
                                 "cycle": None, "last_cycle_age_s": None,
                                 "stalled": []}
                missing += 1
                continue
            snap = rec["snap"]
            age = now - rec["received_at"]
            alive = self.is_alive(age, interval_s)
            stalled = list(snap.get("stalled") or [])
            any_stalled = any_stalled or bool(stalled)
            missing += 0 if alive else 1
            ranks[str(r)] = {
                "alive": alive,
                "last_seen_s": round(age, 3),
                "cycle": snap.get("cycle"),
                "last_cycle_age_s": snap.get("last_cycle_age_s"),
                "stalled": stalled,
            }
        status = ("stalled" if any_stalled
                  else "degraded" if missing else "ok")
        out = {"status": status, "world": self.world,
               "monitor_interval_s": interval_s, "ranks": ranks}
        out.update(self.skew())
        return out
