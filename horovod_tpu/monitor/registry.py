"""Per-rank metric registry (no jax imports).

The local half of the telemetry subsystem (``docs/monitoring.md``): every
process owns one :class:`MetricRegistry` that the engine, the scheduler
primitives, the negotiation response cache, the in-flight ring and the
runtime sanitizer publish into.  The registry is deliberately dumb — three
metric kinds, a flat snapshot dict, and a Prometheus text rendering — so it
can be read by the controller side-channel, the rank-0 HTTP exporter, the
timeline counter track and ``bench.py`` without any of them knowing about
the publishers.

Reference mapping: the reference exposes per-rank state only through the
timeline and log lines; this registry is the missing queryable surface the
Horovod paper's operability story implies (stall warnings, autotune logs,
timeline) — SURVEY.md §5 "observability".

Publishers either own a metric handle (``registry.counter("x").inc()``) or
register a *collector* — a callback run at snapshot time that refreshes
gauges from live objects (``engine``/``scheduler`` state), keeping the hot
dispatch path free of per-event registry calls.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

Number = Union[int, float]

# Default histogram buckets: coordinator-cycle microseconds (spans the
# inline-kick fast path through a slow multi-host negotiation round).
DEFAULT_BUCKETS = (50.0, 100.0, 250.0, 500.0, 1000.0, 2500.0, 5000.0,
                   10000.0, 50000.0, 250000.0, 1000000.0)


def _sanitize(name: str) -> str:
    """Prometheus metric names: [a-zA-Z_:][a-zA-Z0-9_:]*."""
    out = []
    for i, ch in enumerate(name):
        if ch.isalnum() or ch in "_:":
            out.append(ch)
        else:
            out.append("_")
    s = "".join(out)
    if s and s[0].isdigit():
        s = "_" + s
    return s or "_"


class Counter:
    """Monotonically increasing value."""

    kind = "counter"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._value: float = 0

    def inc(self, n: Number = 1) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name!r} cannot decrease")
        with self._lock:
            self._value += n

    @property
    def value(self) -> Number:
        with self._lock:
            return self._value

    def set_total(self, total: Number) -> None:
        """Adopt an externally maintained cumulative total (collectors
        mirroring pre-existing engine counters).  Never moves backwards."""
        with self._lock:
            if total > self._value:
                self._value = total

    def snapshot_value(self):
        return self.value


class Gauge:
    """Point-in-time value."""

    kind = "gauge"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._value: Number = 0

    def set(self, v: Number) -> None:
        with self._lock:
            self._value = v

    def inc(self, n: Number = 1) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: Number = 1) -> None:
        with self._lock:
            self._value -= n

    @property
    def value(self) -> Number:
        with self._lock:
            return self._value

    def snapshot_value(self):
        return self.value


class Histogram:
    """Fixed-bucket cumulative histogram (Prometheus semantics: each bucket
    counts observations ``<= le``; ``+Inf`` is the total count)."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        self.name = name
        self.help = help
        self.buckets: Tuple[float, ...] = tuple(sorted(buckets))
        self._lock = threading.Lock()
        self._counts = [0] * (len(self.buckets) + 1)   # last = +Inf overflow
        self._sum: float = 0.0
        self._count: int = 0

    def observe(self, v: Number) -> None:
        with self._lock:
            self._sum += v
            self._count += 1
            for i, le in enumerate(self.buckets):
                if v <= le:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def snapshot_value(self) -> dict:
        with self._lock:
            cum, out = 0, {}
            for le, c in zip(self.buckets, self._counts):
                cum += c
                out[le] = cum
            return {"count": self._count, "sum": round(self._sum, 3),
                    "buckets": out}

    def percentile(self, q: float) -> Optional[float]:
        """Estimate the q-th percentile (``q`` in [0, 1]) by linear
        interpolation inside the bucket that crosses it — the standard
        Prometheus ``histogram_quantile`` estimate, computed locally so
        ``/metrics`` can export p50/p99 without a query engine (ISSUE 19:
        serving latency SLOs are percentile targets, not means).  None
        until something was observed; observations past the last finite
        bucket clamp to that bound (the estimate cannot exceed what the
        buckets resolve)."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"percentile wants q in [0, 1], got {q}")
        with self._lock:
            total = self._count
            if total == 0 or not self.buckets:
                return None
            target = q * total
            cum = 0
            lo = 0.0
            for le, c in zip(self.buckets, self._counts):
                if c and cum + c >= target:
                    frac = (target - cum) / c
                    return round(lo + (le - lo) * frac, 4)
                cum += c
                lo = le
            return self.buckets[-1]

    def set_cumulative(self, counts: Sequence[int], sum_: float,
                       count: int) -> None:
        """Adopt an externally maintained histogram (collectors mirroring
        a publisher's own per-bucket counts — e.g. the trace recorder's
        per-phase buckets — without per-event registry calls on the hot
        path).  ``counts`` are per-bucket non-cumulative counts aligned
        with ``self.buckets`` plus the +Inf overflow.  Never moves
        backwards, matching ``Counter.set_total`` semantics."""
        counts = list(counts)
        if len(counts) != len(self._counts):
            raise ValueError(
                f"histogram {self.name!r}: expected {len(self._counts)} "
                f"bucket counts, got {len(counts)}")
        with self._lock:
            if count >= self._count:
                self._counts = counts
                self._sum = float(sum_)
                self._count = int(count)


class MetricRegistry:
    """Thread-safe name → metric table with snapshot-time collectors."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, object] = {}
        self._collectors: List[Callable[["MetricRegistry"], None]] = []

    # -------------------------------------------------------- registration
    def _get_or_create(self, cls, name: str, help: str, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help, **kw)
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}")
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, help, buckets=buckets)

    def register_collector(self,
                           fn: Callable[["MetricRegistry"], None]) -> None:
        """``fn(registry)`` runs before every snapshot/render — the place
        to refresh gauges from live engine/scheduler objects without
        touching the hot path per event."""
        with self._lock:
            self._collectors.append(fn)

    # ------------------------------------------------------------- reading
    def _run_collectors(self) -> None:
        with self._lock:
            collectors = list(self._collectors)
        for fn in collectors:
            try:
                fn(self)
            except Exception:  # noqa: BLE001 - telemetry must never raise
                pass

    def get(self, name: str) -> Optional[object]:
        with self._lock:
            return self._metrics.get(name)

    def snapshot(self) -> Dict[str, object]:
        """Flat ``name -> value`` dict (histograms become sub-dicts) —
        the payload the controller side-channel ships to rank 0."""
        self._run_collectors()
        with self._lock:
            metrics = list(self._metrics.values())
        return {m.name: m.snapshot_value() for m in metrics}

    def to_prometheus(self, extra_label: str = "") -> str:
        """Prometheus text exposition format (served at ``/metrics``).

        ``extra_label`` is an optional pre-rendered label body (e.g.
        ``rank="0"``) applied to every series."""
        self._run_collectors()
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        lines: List[str] = []
        lab = "{" + extra_label + "}" if extra_label else ""
        for m in metrics:
            name = _sanitize(m.name)
            if m.help:
                lines.append(f"# HELP {name} {m.help}")
            lines.append(f"# TYPE {name} {m.kind}")
            if isinstance(m, Histogram):
                snap = m.snapshot_value()
                for le, c in snap["buckets"].items():
                    le_lab = f'le="{le:g}"'
                    body = (extra_label + "," + le_lab) if extra_label \
                        else le_lab
                    lines.append(f"{name}_bucket{{{body}}} {c}")
                inf_lab = 'le="+Inf"'
                body = (extra_label + "," + inf_lab) if extra_label \
                    else inf_lab
                lines.append(f"{name}_bucket{{{body}}} {snap['count']}")
                lines.append(f"{name}_sum{lab} {snap['sum']:g}")
                lines.append(f"{name}_count{lab} {snap['count']}")
                # Percentile export (ISSUE 19): pre-computed p50/p99
                # gauges so load balancers / autoscalers without a
                # histogram_quantile engine read latency SLOs directly.
                for q, suffix in ((0.5, "p50"), (0.99, "p99")):
                    v = m.percentile(q)
                    if v is not None:
                        lines.append(f"{name}_{suffix}{lab} {v:g}")
            else:
                lines.append(f"{name}{lab} {m.snapshot_value():g}")
        return "\n".join(lines) + "\n"
