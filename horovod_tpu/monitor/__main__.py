"""``python -m horovod_tpu.monitor`` — pretty-print a live or dumped
fleet snapshot (no jax required).

Usage::

    python -m horovod_tpu.monitor --url http://host:9090    # live exporter
    python -m horovod_tpu.monitor snapshot.json             # dumped file
    python -m horovod_tpu.monitor --url ... --json          # raw JSON
    python -m horovod_tpu.monitor --url ... --watch 2       # refresh loop

The live mode reads the rank-0 HTTP exporter started by
``HOROVOD_MONITOR_PORT`` (``/snapshot``); the file mode reads a JSON dump
of the same shape (e.g. ``curl :9090/snapshot > snap.json``).
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import List, Optional


def _fetch(url: str) -> dict:
    import urllib.request
    base = url.rstrip("/")
    if not base.endswith("/snapshot"):
        base += "/snapshot"
    with urllib.request.urlopen(base, timeout=10) as resp:
        return json.loads(resp.read().decode())


def _fmt(v, suffix: str = "") -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:g}{suffix}"
    return f"{v}{suffix}"


def render(dump: dict) -> str:
    """Human-readable fleet view from a ``/snapshot`` dump."""
    health = dump.get("health", {})
    lines: List[str] = []
    status = health.get("status", "unknown")
    lines.append(f"fleet status: {status.upper()}   "
                 f"world={health.get('world', '?')}   "
                 f"interval={_fmt(health.get('monitor_interval_s'), 's')}")
    skew = health.get("cycle_us_spread")
    if skew is not None:
        lines.append(f"straggler: slowest rank "
                     f"{health.get('slowest_rank')}  "
                     f"cycle-time spread {skew:g} us")
    ranks = health.get("ranks", {})
    if ranks:
        lines.append("")
        lines.append(f"{'rank':>4}  {'alive':>5}  {'cycle':>8}  "
                     f"{'cyc-age':>8}  {'seen':>7}  stalled")
        for r in sorted(ranks, key=lambda k: int(k)):
            info = ranks[r]
            stalled = ",".join(info.get("stalled") or []) or "-"
            lines.append(
                f"{r:>4}  {'yes' if info.get('alive') else 'NO':>5}  "
                f"{_fmt(info.get('cycle')):>8}  "
                f"{_fmt(info.get('last_cycle_age_s'), 's'):>8}  "
                f"{_fmt(info.get('last_seen_s'), 's'):>7}  {stalled}")
    table = dump.get("table", {})
    for r in sorted(table, key=lambda k: int(k)):
        snap = table[r]
        ledger = snap.get("ledger") or []
        if ledger:
            lines.append("")
            lines.append(f"rank {r} ledger tail:")
            lines.extend(f"  {e}" for e in ledger)
    # A few headline metrics per rank, if present.
    heads = ["hvd_negotiation_us_total", "hvd_response_cache_hits_total",
             "hvd_response_cache_misses_total", "hvd_stalled_collectives",
             "hvd_monitor_frame_bytes_total"]
    rows = []
    for r in sorted(table, key=lambda k: int(k)):
        m = table[r].get("metrics") or {}
        if any(h in m for h in heads):
            rows.append((r, [m.get(h) for h in heads]))
    if rows:
        lines.append("")
        lines.append("rank  " + "  ".join(h[len("hvd_"):] for h in heads))
        for r, vals in rows:
            lines.append(f"{r:>4}  " + "  ".join(_fmt(v) for v in vals))
    # Lifecycle phase means from the trace digests (HOROVOD_TRACE armed):
    # which host-side phase eats the cycle, per rank (docs/timeline.md).
    phase_rows = []
    phase_names = None
    for r in sorted(table, key=lambda k: int(k)):
        tr = table[r].get("trace") or {}
        phases = tr.get("phases")
        if not phases:
            continue
        if phase_names is None:
            phase_names = list(phases)
        means = []
        for p in phase_names:
            total, count = (phases.get(p) or [0, 0])[:2]
            means.append(round(total / count, 1) if count else None)
        phase_rows.append((r, tr.get("spans"), means, tr.get("cycle_us")))
    if phase_rows:
        lines.append("")
        lines.append("lifecycle phases, mean us (trace digests):")
        lines.append("rank  spans  "
                     + "  ".join(f"{p:>11}" for p in phase_names)
                     + f"  {'cycle':>9}")
        for r, spans, means, cyc in phase_rows:
            lines.append(f"{r:>4}  {_fmt(spans):>5}  "
                         + "  ".join(f"{_fmt(v):>11}" for v in means)
                         + f"  {_fmt(cyc):>9}")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m horovod_tpu.monitor",
        description="Pretty-print a horovod_tpu fleet telemetry snapshot")
    p.add_argument("file", nargs="?",
                   help="dumped /snapshot JSON file (omit with --url)")
    p.add_argument("--url", help="live exporter base URL "
                                 "(http://host:HOROVOD_MONITOR_PORT)")
    p.add_argument("--json", action="store_true",
                   help="print the raw JSON instead of the table")
    p.add_argument("--watch", type=float, default=0.0, metavar="SECONDS",
                   help="refresh the live view every N seconds")
    args = p.parse_args(argv)
    if bool(args.file) == bool(args.url):
        p.error("pass exactly one of: a snapshot file, or --url")
    if args.watch and not args.url:
        p.error("--watch needs --url")

    def once() -> int:
        if args.url:
            try:
                dump = _fetch(args.url)
            except Exception as exc:  # noqa: BLE001 - CLI surface
                print(f"error: could not fetch {args.url}: {exc}",
                      file=sys.stderr)
                return 1
        else:
            try:
                with open(args.file) as fh:
                    dump = json.load(fh)
            except (OSError, ValueError) as exc:
                print(f"error: could not read {args.file}: {exc}",
                      file=sys.stderr)
                return 1
        print(json.dumps(dump, indent=2) if args.json else render(dump))
        return 0

    if not args.watch:
        return once()
    try:
        while True:
            print("\x1b[2J\x1b[H", end="")      # clear screen
            rc = once()
            if rc:
                return rc
            time.sleep(args.watch)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
