"""DLRM — BASELINE config #5 ("DLRM with alltoall embedding exchange").

The reference's role for ``hvd.alltoall`` (SURVEY.md §2c "expert/embedding
parallel via alltoall"): recommendation models shard their huge embedding
tables across ranks (model parallel) while MLPs run data parallel; each
step exchanges looked-up embedding rows with one alltoall so every rank
gets the embeddings for ITS batch shard from every table shard.

TPU-native layout: tables sharded over the ``ep`` axis (table-parallel —
each ep rank owns ``n_tables/ep`` whole tables), batch over ``dp``.  The
exchange is ``lax.all_to_all`` over ep, riding ICI.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import PartitionSpec as P
from ..compat import axis_size as compat_axis_size


@dataclasses.dataclass(frozen=True)
class DLRMConfig:
    n_tables: int = 8                 # total sparse features
    rows_per_table: int = 1000
    embed_dim: int = 32
    dense_dim: int = 13
    bottom_mlp: Tuple[int, ...] = (64, 32)
    top_mlp: Tuple[int, ...] = (64, 32, 1)
    dtype: Any = jnp.float32
    dp_axis: Optional[str] = "dp"
    ep_axis: Optional[str] = "ep"


def tiny(**kw) -> DLRMConfig:
    return DLRMConfig(**kw)


def _mlp_params(key, dims, dtype):
    ps = []
    for i in range(len(dims) - 1):
        key, k = jax.random.split(key)
        ps.append({"w": (jax.random.normal(k, (dims[i], dims[i + 1]),
                                           jnp.float32)
                         / np.sqrt(dims[i])).astype(dtype),
                   "b": jnp.zeros((dims[i + 1],), dtype)})
    return ps


def init_params(cfg: DLRMConfig, key) -> Dict:
    """Tables are stored STACKED [n_tables, rows, dim] so the ep sharding is
    one leading-axis partition (tables_per_rank = n_tables/ep)."""
    k1, k2, k3 = jax.random.split(key, 3)
    tables = (jax.random.normal(
        k1, (cfg.n_tables, cfg.rows_per_table, cfg.embed_dim), jnp.float32)
        * 0.01).astype(cfg.dtype)
    n_feats = cfg.embed_dim * cfg.n_tables
    inter_in = cfg.bottom_mlp[-1] + n_feats
    return {
        "tables": tables,
        "bottom": _mlp_params(k2, (cfg.dense_dim,) + cfg.bottom_mlp, cfg.dtype),
        "top": _mlp_params(k3, (inter_in,) + cfg.top_mlp, cfg.dtype),
    }


def param_specs(cfg: DLRMConfig) -> Dict:
    n_bottom = len(cfg.bottom_mlp)
    n_top = len(cfg.top_mlp)
    return {
        "tables": P(cfg.ep_axis),
        "bottom": [{"w": P(), "b": P()} for _ in range(n_bottom)],
        "top": [{"w": P(), "b": P()} for _ in range(n_top)],
    }


def _mlp(x, layers, final_act=None):
    for i, l in enumerate(layers):
        x = x @ l["w"] + l["b"]
        if i < len(layers) - 1:
            x = jax.nn.relu(x)
        elif final_act is not None:
            x = final_act(x)
    return x


def _embedding_exchange(tables_local, sparse_ids, cfg: DLRMConfig):
    """Lookup + alltoall (the reference's ``hvd.alltoall`` hot path).

    Hybrid-parallel layout: the batch is sharded over dp AND ep (data spec
    ``P(("dp", "ep"))``); tables are sharded over ep.  Per step:

    1. allgather the (small) id matrix over ep so this rank sees the ids of
       every ep-peer's batch slice;
    2. look up this rank's local tables for that combined batch;
    3. ONE alltoall redistributes the (large) embedding rows so each rank
       ends with all-table embeddings for exactly its own batch slice —
       the op the reference's DLRM config exists to exercise.

    tables_local: [n_tables/ep, rows, dim]; sparse_ids: [B_loc, n_tables].
    """
    ep = compat_axis_size(cfg.ep_axis) if cfg.ep_axis else 1
    t_loc = tables_local.shape[0]
    if not cfg.ep_axis or ep == 1:
        looked = jax.vmap(lambda tbl, ids: tbl[ids], in_axes=(0, 1),
                          out_axes=1)(tables_local, sparse_ids)
        return looked.reshape(looked.shape[0], -1)
    ep_idx = lax.axis_index(cfg.ep_axis)
    ids_all = lax.all_gather(sparse_ids, cfg.ep_axis, axis=0, tiled=True)
    my_ids = lax.dynamic_slice_in_dim(ids_all, ep_idx * t_loc, t_loc, 1)
    # [B_loc*ep, t_loc, dim]: my tables' rows for every ep-peer's slice
    looked = jax.vmap(lambda tbl, ids: tbl[ids], in_axes=(0, 1),
                      out_axes=1)(tables_local, my_ids)
    # alltoall: batch slices out, table groups in -> [B_loc, n_tables, dim]
    exchanged = lax.all_to_all(looked, cfg.ep_axis, split_axis=0,
                               concat_axis=1, tiled=True)
    return exchanged.reshape(exchanged.shape[0], -1)


def forward(params, dense, sparse_ids, cfg: DLRMConfig):
    """dense [B, dense_dim], sparse_ids [B, n_tables] -> logits [B]."""
    bottom_out = _mlp(dense, params["bottom"])
    emb = _embedding_exchange(params["tables"], sparse_ids, cfg)
    interact = jnp.concatenate([bottom_out, emb.astype(bottom_out.dtype)],
                               axis=-1)
    return _mlp(interact, params["top"])[:, 0]


def loss_fn(params, dense, sparse_ids, labels, cfg: DLRMConfig):
    """Partial BCE loss (sum semantics over dp; ep compute is not redundant
    for tables — each rank owns distinct tables — but the MLP compute is
    replicated over ep, handled by the denominators in sync_grads)."""
    logits = forward(params, dense, sparse_ids, cfg).astype(jnp.float32)
    bce = optax.sigmoid_binary_cross_entropy(logits, labels.astype(jnp.float32))
    denom = float(bce.size)
    for ax in (cfg.dp_axis, cfg.ep_axis):
        if ax:
            denom = denom * compat_axis_size(ax)
    return jnp.sum(bce) / denom


def psum_loss(loss_partial, cfg: DLRMConfig):
    for ax in (cfg.dp_axis, cfg.ep_axis):
        if ax:
            loss_partial = lax.psum(loss_partial, ax)
    return loss_partial


def sync_grads(grads, cfg: DLRMConfig):
    """dp psum for everything; ep psum only for ep-replicated params (MLPs).
    Table grads stay local to their ep shard."""
    specs = param_specs(cfg)

    def leaf_sync(g, spec):
        if cfg.dp_axis:
            g = lax.psum(g, cfg.dp_axis)
        if cfg.ep_axis and all(s != cfg.ep_axis for s in spec):
            g = lax.psum(g, cfg.ep_axis)
        return g

    return jax.tree_util.tree_map(leaf_sync, grads, specs,
                                  is_leaf=lambda x: isinstance(x, P))


def make_train_step(cfg: DLRMConfig, optimizer):
    def step(params, opt_state, dense, sparse_ids, labels):
        loss_partial, grads = jax.value_and_grad(loss_fn)(
            params, dense, sparse_ids, labels, cfg)
        grads = sync_grads(grads, cfg)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, psum_loss(loss_partial, cfg)

    return step


def synthetic_batch(cfg: DLRMConfig, batch: int, seed: int = 0):
    rng = np.random.RandomState(seed)
    dense = rng.randn(batch, cfg.dense_dim).astype(np.float32)
    sparse = rng.randint(0, cfg.rows_per_table,
                         size=(batch, cfg.n_tables)).astype(np.int32)
    labels = rng.randint(0, 2, size=(batch,)).astype(np.int32)
    return dense, sparse, labels
