"""Vision Transformer (ViT) classification family.

Beyond the reference's model zoo (Horovod ships only wrapper examples —
SURVEY.md P14): an image-classification transformer that REUSES the bert
encoder blocks (`bert._attention` / `bert._ffn` / `bert._layernorm`) so
the Megatron-style tp sharding, flash routing, and layernorm numerics
have one source of truth.  The ViT-specific pieces are patch
embedding (a single [P*P*C, D] matmul — space-to-depth then project,
which XLA fuses; no conv needed), a CLS token, learned positional
embeddings, and a classification head.

Sharding: dp over the batch, tp through the reused encoder blocks
(column-split qkv/w_in, row-split wo/w_out with psum).  The patch
sequence is short (e.g. 197 at 224/16), so sequence parallelism is
deliberately unsupported here — set ``sp_axis=None``; long-context
machinery lives in the llama/bert families.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P
from ..compat import axis_size as compat_axis_size

from . import bert as _bert


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    channels: int = 3
    n_classes: int = 1000
    d_model: int = 768           # ViT-Base
    n_layers: int = 12
    n_heads: int = 12
    d_ff: int = 3072
    dtype: Any = jnp.bfloat16
    dp_axis: Optional[str] = "dp"
    tp_axis: Optional[str] = "tp"
    # Required by the reused bert blocks; ViT keeps it None (short
    # patch sequences — see module docstring).
    sp_axis: Optional[str] = None
    use_flash: Optional[bool] = None

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def n_patches(self) -> int:
        g = self.image_size // self.patch_size
        return g * g

    def __post_init__(self):
        if self.image_size % self.patch_size:
            raise ValueError(f"image_size {self.image_size} not divisible "
                             f"by patch_size {self.patch_size}")
        if self.d_model % self.n_heads:
            raise ValueError("d_model must divide by n_heads")
        if self.sp_axis is not None:
            raise ValueError("ViT does not support sequence parallelism "
                             "(short patch sequences); set sp_axis=None")


def vit_b16() -> ViTConfig:
    return ViTConfig()


def tiny(**kw) -> ViTConfig:
    defaults = dict(image_size=32, patch_size=8, channels=3, n_classes=10,
                    d_model=64, n_layers=2, n_heads=4, d_ff=128)
    defaults.update(kw)
    return ViTConfig(**defaults)


def init_params(cfg: ViTConfig, key) -> Dict:
    k = iter(jax.random.split(key, 4 + 6 * cfg.n_layers))
    D, H, Hd, F = cfg.d_model, cfg.n_heads, cfg.head_dim, cfg.d_ff
    dt = cfg.dtype
    pdim = cfg.patch_size * cfg.patch_size * cfg.channels

    def dense(key, fan_in, shape):
        return (jax.random.normal(key, shape, jnp.float32)
                * (1.0 / np.sqrt(fan_in))).astype(dt)

    layers = []
    for _ in range(cfg.n_layers):
        layers.append({
            "ln1_scale": jnp.ones((D,), dt), "ln1_bias": jnp.zeros((D,), dt),
            "wq": dense(next(k), D, (D, H * Hd)),
            "wk": dense(next(k), D, (D, H * Hd)),
            "wv": dense(next(k), D, (D, H * Hd)),
            "wo": dense(next(k), H * Hd, (H * Hd, D)),
            "ln2_scale": jnp.ones((D,), dt), "ln2_bias": jnp.zeros((D,), dt),
            "w_in": dense(next(k), D, (D, F)),
            "b_in": jnp.zeros((F,), dt),
            "w_out": dense(next(k), F, (F, D)),
            "b_out": jnp.zeros((D,), dt),
        })
    return {
        "patch_proj": dense(next(k), pdim, (pdim, D)),
        "cls": jnp.zeros((1, 1, D), dt),
        "pos_embed": dense(next(k), D, (cfg.n_patches + 1, D)),
        "layers": layers,
        "final_ln_scale": jnp.ones((D,), dt),
        "final_ln_bias": jnp.zeros((D,), dt),
        "head": dense(next(k), D, (D, cfg.n_classes)),
    }


def param_specs(cfg: ViTConfig) -> Dict:
    tp = cfg.tp_axis
    layer = {
        "ln1_scale": P(), "ln1_bias": P(),
        "wq": P(None, tp), "wk": P(None, tp), "wv": P(None, tp),
        "wo": P(tp, None),
        "ln2_scale": P(), "ln2_bias": P(),
        "w_in": P(None, tp), "b_in": P(tp),
        "w_out": P(tp, None), "b_out": P(),
    }
    return {
        "patch_proj": P(), "cls": P(), "pos_embed": P(),
        "layers": [dict(layer) for _ in range(cfg.n_layers)],
        "final_ln_scale": P(), "final_ln_bias": P(),
        "head": P(),
    }


def _patchify(images, cfg: ViTConfig):
    """[B, H, W, C] -> [B, N, P*P*C] (space-to-depth, pure reshape /
    transpose — XLA fuses it into the projection matmul)."""
    B, Himg, Wimg, C = images.shape
    Ps = cfg.patch_size
    g_h, g_w = Himg // Ps, Wimg // Ps
    x = images.reshape(B, g_h, Ps, g_w, Ps, C)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(B, g_h * g_w, Ps * Ps * C)


def forward(params, images, cfg: ViTConfig):
    """CLS-token encoder state for the local image shard
    [B_loc, H, W, C] -> [B_loc, D]."""
    x = _patchify(images.astype(cfg.dtype), cfg) @ params["patch_proj"]
    B, N, D = x.shape
    cls = jnp.broadcast_to(params["cls"], (B, 1, D)).astype(x.dtype)
    x = jnp.concatenate([cls, x], axis=1) + params["pos_embed"][None]
    for p in params["layers"]:
        x = x + _bert._attention(
            _bert._layernorm(x, p["ln1_scale"], p["ln1_bias"]), p, cfg)
        x = x + _bert._ffn(
            _bert._layernorm(x, p["ln2_scale"], p["ln2_bias"]), p, cfg)
    x = _bert._layernorm(x, params["final_ln_scale"],
                         params["final_ln_bias"])
    return x[:, 0]


def logits(params, images, cfg: ViTConfig):
    return (forward(params, images, cfg)
            @ params["head"]).astype(jnp.float32)


def loss_fn(params, images, labels, cfg: ViTConfig):
    """Partial cross-entropy (sum-semantics, like bert.mlm_loss_fn): the
    denominator is the GLOBAL example count (psum over dp) times tp for
    the redundant tensor-parallel compute."""
    lg = logits(params, images, cfg)
    logp = jax.nn.log_softmax(lg, axis=-1)
    nll = -jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]
    local_sum = jnp.sum(nll)
    denom = jnp.asarray(labels.shape[0], jnp.float32)
    if cfg.dp_axis:
        denom = lax.psum(denom, cfg.dp_axis)
    if cfg.tp_axis:
        denom = denom * compat_axis_size(cfg.tp_axis)
    return local_sum / denom


def psum_loss(loss_partial, cfg: ViTConfig):
    for ax in (cfg.dp_axis, cfg.tp_axis):
        if ax:
            loss_partial = lax.psum(loss_partial, ax)
    return loss_partial


def sync_grads(grads, cfg: ViTConfig, specs=None):
    # bert.sync_grads reads only dp/sp/tp axis names + the specs tree, so
    # it serves ViT verbatim with ViT's own specs.
    return _bert.sync_grads(grads, cfg, specs=specs or param_specs(cfg))


def make_train_step(cfg: ViTConfig, optimizer):
    import optax

    def step(params, opt_state, images, labels):
        loss_partial, grads = jax.value_and_grad(loss_fn)(
            params, images, labels, cfg)
        grads = sync_grads(grads, cfg)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, psum_loss(loss_partial, cfg)

    return step
