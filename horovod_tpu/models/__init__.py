"""Model zoo namespace (docs/models.md).

Lazy submodule access: ``horovod_tpu.models.llama`` works after
``import horovod_tpu.models`` without importing every family (and its
framework deps) eagerly.
"""

_FAMILIES = ("llama", "gpt2", "bert", "vit", "resnet", "moe", "dlrm",
             "mnist", "convert")

__all__ = list(_FAMILIES)


def __getattr__(name):
    if name in _FAMILIES:
        import importlib
        mod = importlib.import_module("." + name, __name__)
        globals()[name] = mod          # cache for next access
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(_FAMILIES))
