"""Mixture-of-Experts with expert parallelism over the ``ep`` mesh axis.

Reference position: Horovod ships the PRIMITIVE this is built on —
``hvd.alltoall`` for DLRM-style embedding exchange (SURVEY.md §2c
"expert/embedding parallel via alltoall", BASELINE config #5) — but no MoE
layer; this module is the beyond-parity model family that turns the
primitive into a working sparse layer, TPU-first:

- **Static shapes everywhere** (XLA requirement): Switch-Transformer-style
  capacity-factor routing — every expert processes exactly ``capacity``
  token slots per source rank; over-capacity tokens are dropped (their
  output is the residual identity), under-capacity slots are zero padding.
- **Dispatch/combine are einsums** against a one-hot dispatch mask (the
  standard TPU formulation — no gather/scatter, everything rides the MXU).
- **Expert parallelism**: experts are sharded over ``ep``; the dispatched
  [E, C, D] buffer is exchanged with ONE ``lax.all_to_all`` over ICI so
  each rank runs only its local experts on every rank's tokens, and a
  second all_to_all brings expert outputs home (exactly the exchange the
  reference's DLRM config does for embeddings).
- **Load-balancing auxiliary loss** (Shazeer/Switch): mean(gate fraction ·
  token fraction) · E, summed across ranks by the caller's loss psum.

Layout: tokens ``[S, D]`` per rank (callers flatten [B, T]); experts'
FFN params ``{"w1": [E, D, F], "w2": [E, F, D]}`` stacked on the expert
axis — shard over ``ep`` with ``param_specs``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    d_model: int = 64
    d_ff: int = 128
    n_experts: int = 8
    capacity_factor: float = 1.25
    ep_axis: Optional[str] = "ep"      # None = all experts local
    router_noise: float = 0.0          # jitter std during training
    dtype: Any = jnp.float32

    def capacity(self, tokens_per_rank: int) -> int:
        """Per-(source-rank, expert) token slots: static by construction."""
        return max(1, int(np.ceil(tokens_per_rank / self.n_experts
                                  * self.capacity_factor)))


def init_params(cfg: MoEConfig, key) -> Dict:
    kr, k1, k2 = jax.random.split(key, 3)
    E, D, F = cfg.n_experts, cfg.d_model, cfg.d_ff
    s1, s2 = 1.0 / np.sqrt(D), 1.0 / np.sqrt(F)
    return {
        "router": (jax.random.normal(kr, (D, E), jnp.float32) * s1
                   ).astype(cfg.dtype),
        "w1": (jax.random.normal(k1, (E, D, F), jnp.float32) * s1
               ).astype(cfg.dtype),
        "w2": (jax.random.normal(k2, (E, F, D), jnp.float32) * s2
               ).astype(cfg.dtype),
    }


def param_specs(cfg: MoEConfig) -> Dict:
    ep = cfg.ep_axis
    return {"router": P(), "w1": P(ep), "w2": P(ep)}


def _route(x, router_w, cfg: MoEConfig, rng: Optional[jax.Array]):
    """Top-1 routing with static capacity.

    Returns (dispatch [S, E, C] one-hot, combine [S, E, C] gate-weighted,
    aux_loss scalar).  Position of a token within its expert's capacity
    buffer comes from a cumsum over the expert's one-hot column —
    deterministic, order-preserving, shape-static.
    """
    S = x.shape[0]
    C = cfg.capacity(S)
    logits = (x.astype(jnp.float32)
              @ router_w.astype(jnp.float32))          # [S, E]
    if cfg.router_noise > 0.0:
        if rng is None:
            raise ValueError(
                "MoEConfig.router_noise > 0 requires passing rng= to "
                "moe_ffn (the bundled lm_loss training path is "
                "deterministic and does not thread one)")
        logits = logits + cfg.router_noise * jax.random.normal(
            rng, logits.shape, jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)                # [S]
    onehot = jax.nn.one_hot(expert, cfg.n_experts,
                            dtype=jnp.float32)         # [S, E]
    gate = jnp.sum(probs * onehot, axis=-1)            # [S]

    # Position within the expert's buffer; tokens past capacity drop out.
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1.0    # [S, E], -1 if other
    pos_in_expert = jnp.sum(pos * onehot, axis=-1).astype(jnp.int32)  # [S]
    keep = (pos_in_expert < C) & (pos_in_expert >= 0)
    pos_oh = jax.nn.one_hot(pos_in_expert, C, dtype=jnp.float32)  # [S, C]
    dispatch = (onehot * keep[:, None])[:, :, None] * pos_oh[:, None, :]
    combine = dispatch * gate[:, None, None]

    # Switch aux loss: fraction of tokens vs fraction of router mass.
    token_frac = jnp.mean(onehot, axis=0)              # [E]
    prob_frac = jnp.mean(probs, axis=0)                # [E]
    aux = jnp.sum(token_frac * prob_frac) * cfg.n_experts
    return dispatch, combine, aux


def moe_ffn(x, params, cfg: MoEConfig,
            rng: Optional[jax.Array] = None) -> Tuple[jax.Array, jax.Array]:
    """Apply the MoE FFN to per-rank tokens ``x [S, D]``.

    Inside shard_map with ``ep`` bound, ``params["w1"]/["w2"]`` are the
    LOCAL expert slab [E/ep, D, F] and the dispatch/return exchanges ride
    two ``lax.all_to_all``; without ``ep_axis`` every expert is local.
    Returns ``(y [S, D], aux_loss)`` — dropped tokens yield zeros (callers
    add the residual).
    """
    S, D = x.shape
    E = cfg.n_experts
    C = cfg.capacity(S)
    dispatch, combine, aux = _route(x, params["router"], cfg, rng)

    # [E, C, D] expert buffers (einsum dispatch — MXU, no scatter).
    buf = jnp.einsum("sec,sd->ecd", dispatch.astype(x.dtype), x)

    ep = lax.axis_size(cfg.ep_axis) if cfg.ep_axis else 1
    if ep > 1:
        if E % ep:
            raise ValueError(f"n_experts={E} must divide by ep={ep}")
        # Send each expert's buffer to its home rank; receive every rank's
        # buffers for OUR local experts, stacked along capacity:
        # [E, C, D] -> [E/ep, ep*C, D].
        buf = lax.all_to_all(buf, cfg.ep_axis, split_axis=0, concat_axis=1,
                             tiled=True)

    h = jnp.einsum("ecd,edf->ecf", buf, params["w1"])
    h = jax.nn.silu(h)
    out = jnp.einsum("ecf,efd->ecd", h, params["w2"])

    if ep > 1:
        # Return trip: split the stacked capacity axis back per source
        # rank and send each chunk home -> [E, C, D] of OUR tokens'
        # outputs (chunk j went to rank j and comes back from rank j, so
        # expert-block order is preserved).
        out = lax.all_to_all(out, cfg.ep_axis, split_axis=1, concat_axis=0,
                             tiled=True)

    y = jnp.einsum("sec,ecd->sd", combine.astype(x.dtype), out)
    return y, aux.astype(jnp.float32)


# ----------------------------------------------------------- tiny LM model
@dataclasses.dataclass(frozen=True)
class MoELMConfig:
    """Minimal MoE language model (embed → N × [attention-free mixer +
    MoE FFN] → head) — the test/bench vehicle for expert parallelism."""
    vocab_size: int = 256
    d_model: int = 64
    n_layers: int = 2
    moe: MoEConfig = dataclasses.field(default_factory=MoEConfig)
    aux_weight: float = 0.01
    dp_axis: Optional[str] = "dp"


def lm_init(cfg: MoELMConfig, key) -> Dict:
    keys = jax.random.split(key, 2 + cfg.n_layers)
    D = cfg.d_model
    return {
        "embed": (jax.random.normal(keys[0], (cfg.vocab_size, D),
                                    jnp.float32) / np.sqrt(D)).astype(
            cfg.moe.dtype),
        "layers": [init_params(cfg.moe, keys[1 + i])
                   for i in range(cfg.n_layers)],
        "head": (jax.random.normal(keys[-1], (D, cfg.vocab_size),
                                   jnp.float32) / np.sqrt(D)).astype(
            cfg.moe.dtype),
    }


def lm_param_specs(cfg: MoELMConfig) -> Dict:
    return {"embed": P(), "head": P(),
            "layers": [param_specs(cfg.moe) for _ in range(cfg.n_layers)]}


def lm_loss(params, tokens, targets, cfg: MoELMConfig):
    """Per-rank partial mean loss (same sum-semantics convention as
    models/llama.py): scaled so psum over dp AND ep recovers the global
    mean — ep is a DATA split here (GShard-style: every (dp, ep)
    coordinate routes its own token shard; only experts live on ep)."""
    B, T = tokens.shape
    x = params["embed"][tokens].reshape(B * T, -1)
    aux_total = 0.0
    for lp in params["layers"]:
        y, aux = moe_ffn(x, lp, cfg.moe)
        x = x + y
        aux_total = aux_total + aux
    logits = (x @ params["head"]).astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets.reshape(-1)[:, None],
                               axis=-1)[:, 0]
    denom = float(nll.size)
    for ax in (cfg.dp_axis, cfg.moe.ep_axis):
        if ax:
            denom = denom * lax.axis_size(ax)
    return (jnp.sum(nll) + cfg.aux_weight * aux_total
            * float(nll.size)) / denom


def lm_sync_grads(grads, cfg: MoELMConfig):
    """psum over dp for everything; over ep only for ep-REPLICATED leaves
    (router/embed/head) — expert slabs are exact per rank (each rank
    computed its own experts' full gradient)."""
    specs = lm_param_specs(cfg)

    def leaf(g, spec):
        if cfg.dp_axis:
            g = lax.psum(g, cfg.dp_axis)
        ep = cfg.moe.ep_axis
        if ep and all(s != ep for s in spec):
            g = lax.psum(g, ep)
        return g

    return jax.tree_util.tree_map(leaf, grads, specs,
                                  is_leaf=lambda s: isinstance(s, P))


def make_train_step(cfg: MoELMConfig, optimizer):
    import optax

    def step(params, opt_state, tokens, targets):
        loss_p, grads = jax.value_and_grad(lm_loss)(params, tokens,
                                                    targets, cfg)
        grads = lm_sync_grads(grads, cfg)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        for ax in (cfg.dp_axis, cfg.moe.ep_axis):
            if ax:
                loss_p = lax.psum(loss_p, ax)
        return params, opt_state, loss_p

    return step
