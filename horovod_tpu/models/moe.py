"""Mixture-of-Experts with expert parallelism over the ``ep`` mesh axis.

Reference position: Horovod ships the PRIMITIVE this is built on —
``hvd.alltoall`` for DLRM-style embedding exchange (SURVEY.md §2c
"expert/embedding parallel via alltoall", BASELINE config #5) — but no MoE
layer; this module is the beyond-parity model family that turns the
primitive into a working sparse layer, TPU-first:

- **Static shapes everywhere** (XLA requirement): Switch-Transformer-style
  capacity-factor routing — every expert processes exactly ``capacity``
  token slots per source rank; over-capacity tokens are dropped (their
  output is the residual identity), under-capacity slots are zero padding.
- **Dispatch/combine are einsums** against a one-hot dispatch mask (the
  standard TPU formulation — no gather/scatter, everything rides the MXU).
- **Expert parallelism**: experts are sharded over ``ep``; the dispatched
  [E, C, D] buffer is exchanged with ONE ``lax.all_to_all`` over ICI so
  each rank runs only its local experts on every rank's tokens, and a
  second all_to_all brings expert outputs home (exactly the exchange the
  reference's DLRM config does for embeddings).
- **Load-balancing auxiliary loss** (Shazeer/Switch): mean(gate fraction ·
  token fraction) · E, summed across ranks by the caller's loss psum.

Layout: tokens ``[S, D]`` per rank (callers flatten [B, T]); experts'
FFN params ``{"w1": [E, D, F], "w2": [E, F, D]}`` stacked on the expert
axis — shard over ``ep`` with ``param_specs``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P
from ..compat import axis_size as compat_axis_size


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    d_model: int = 64
    d_ff: int = 128
    n_experts: int = 8
    capacity_factor: float = 1.25
    ep_axis: Optional[str] = "ep"      # None = all experts local
    router_noise: float = 0.0          # jitter std during training
    # Routing family: "tokens" = token-choice (each token picks its
    # top-k experts; Switch/GShard) — "expert_choice" = each expert
    # picks its top-C tokens (Zhou et al. 2022): perfect static load
    # balance by construction (every expert exactly full, no aux loss
    # needed), tokens may be served by 0..E experts (0 ⇒ residual
    # identity, like a capacity drop).
    router_mode: str = "tokens"
    # Experts per token: 1 = Switch (raw top-1 gate), k>=2 = GShard-style
    # top-k with gates NORMALIZED over the selected experts.  Token-choice
    # only (expert_choice fixes fan-in via capacity instead).
    router_top_k: int = 1
    # ST-MoE router z-loss weight (mean logsumexp(logits)^2): keeps router
    # logits small/stable in bf16 training.  0 = off.  Applied by the
    # training paths (lm_loss here, llama.loss_fn) as an ABSOLUTE weight,
    # like aux_weight.
    router_z_weight: float = 0.0
    # SwiGLU experts (Mixtral / the dense llama MLP shape): each expert
    # gains an up-projection w3 and computes (silu(x·w1) ⊙ (x·w3))·w2
    # instead of silu(x·w1)·w2.
    gated: bool = False
    dtype: Any = jnp.float32

    def capacity(self, tokens_per_rank: int) -> int:
        """Per-(source-rank, expert) token slots: static by construction.
        Top-k routing makes k assignments per token, so the slot budget
        scales with k (GShard's capacity definition)."""
        return max(1, int(np.ceil(tokens_per_rank * self.router_top_k
                                  / self.n_experts
                                  * self.capacity_factor)))


def init_params(cfg: MoEConfig, key) -> Dict:
    kr, k1, k2, k3 = jax.random.split(key, 4)
    E, D, F = cfg.n_experts, cfg.d_model, cfg.d_ff
    s1, s2 = 1.0 / np.sqrt(D), 1.0 / np.sqrt(F)
    p = {
        "router": (jax.random.normal(kr, (D, E), jnp.float32) * s1
                   ).astype(cfg.dtype),
        "w1": (jax.random.normal(k1, (E, D, F), jnp.float32) * s1
               ).astype(cfg.dtype),
        "w2": (jax.random.normal(k2, (E, F, D), jnp.float32) * s2
               ).astype(cfg.dtype),
    }
    if cfg.gated:
        p["w3"] = (jax.random.normal(k3, (E, D, F), jnp.float32) * s1
                   ).astype(cfg.dtype)
    return p


def param_specs(cfg: MoEConfig) -> Dict:
    ep = cfg.ep_axis
    specs = {"router": P(), "w1": P(ep), "w2": P(ep)}
    if cfg.gated:
        specs["w3"] = P(ep)
    return specs


def _route(x, router_w, cfg: MoEConfig, rng: Optional[jax.Array]):
    """Top-k routing with static capacity (Switch for k=1, GShard for
    k>=2).

    Returns (dispatch [S, E, C] one-hot, combine [S, E, C] gate-weighted,
    aux_loss scalar, z_loss scalar).  Position of a token within its
    expert's capacity buffer comes from a cumsum over the expert's
    one-hot column, with later choices slotted AFTER all earlier
    choices' tokens (choice priority: a token's second expert never
    evicts another token's first) — deterministic, order-preserving,
    shape-static.

    Gates: k=1 uses the raw router probability (Switch); k>=2 normalizes
    the selected probabilities to sum to 1 (GShard) so the combined
    output is a convex mixture of the chosen experts.
    """
    S = x.shape[0]
    E = cfg.n_experts
    K = cfg.router_top_k
    if cfg.router_mode not in ("tokens", "expert_choice"):
        raise ValueError(f"router_mode must be 'tokens' or "
                         f"'expert_choice', got {cfg.router_mode!r}")
    if cfg.router_mode == "expert_choice" and K != 1:
        raise ValueError("expert_choice routing fixes per-expert fan-in "
                         "via capacity; router_top_k must stay 1")
    if not 1 <= K <= E:
        raise ValueError(f"router_top_k={K} must be in [1, {E}]")
    C = cfg.capacity(S)
    logits = (x.astype(jnp.float32)
              @ router_w.astype(jnp.float32))          # [S, E]
    if cfg.router_noise > 0.0:
        if rng is None:
            raise ValueError(
                "MoEConfig.router_noise > 0 requires threading rng= "
                "through moe_ffn / lm_loss / llama loss_fn")
        logits = logits + cfg.router_noise * jax.random.normal(
            rng, logits.shape, jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    # ST-MoE router z-loss: penalize large logits (logsumexp^2) — applied
    # by the caller with cfg.router_z_weight.
    z = jax.scipy.special.logsumexp(logits, axis=-1)   # [S]
    z_loss = jnp.mean(jnp.square(z))

    if cfg.router_mode == "expert_choice":
        if C > S:
            raise ValueError(f"expert_choice capacity {C} exceeds tokens "
                             f"{S}; lower capacity_factor")
        # Each expert takes its top-C tokens by router prob: [E, C]
        # scores + token ids.  top_k's gradient flows to the selected
        # probs through g; selection itself is non-differentiable, as in
        # every hard router.
        g, idx = lax.top_k(probs.T, C)                 # [E, C]
        dispatch = jax.nn.one_hot(idx, S,
                                  dtype=jnp.float32)   # [E, C, S]
        dispatch = dispatch.transpose(2, 0, 1)         # [S, E, C]
        combine = dispatch * g[None, :, :]
        # Perfectly balanced by construction: aux is identically its
        # floor (1.0-equivalent) — report 0 so aux_weight has no effect.
        return dispatch, combine, jnp.zeros((), jnp.float32), z_loss

    # Iterative argmax over the k choices; positions are cumulative
    # across choices via per-expert counts.
    masked = probs
    counts = jnp.zeros((E,), jnp.float32)
    disp_ks, gate_ks = [], []
    first_onehot = None
    for k in range(K):
        onehot = jax.nn.one_hot(jnp.argmax(masked, axis=-1), E,
                                dtype=jnp.float32)     # [S, E]
        if first_onehot is None:
            first_onehot = onehot
        gate_ks.append(jnp.sum(probs * onehot, axis=-1))   # raw prob [S]
        pos = ((jnp.cumsum(onehot, axis=0) + counts[None, :]) * onehot
               - 1.0)                                  # [S, E]
        pos_in_expert = jnp.sum(pos * onehot, axis=-1).astype(jnp.int32)
        keep = (pos_in_expert < C) & (pos_in_expert >= 0)
        pos_oh = jax.nn.one_hot(pos_in_expert, C, dtype=jnp.float32)
        disp_ks.append((onehot * keep[:, None])[:, :, None]
                       * pos_oh[:, None, :])           # [S, E, C]
        counts = counts + jnp.sum(onehot, axis=0)
        masked = masked * (1.0 - onehot)

    if K > 1:
        denom = sum(gate_ks) + 1e-9
        gate_ks = [g / denom for g in gate_ks]
    dispatch = sum(disp_ks)
    combine = sum(g[:, None, None] * d for g, d in zip(gate_ks, disp_ks))

    # Load-balance aux loss (Switch/GShard): fraction of tokens whose
    # FIRST choice is expert e vs fraction of router mass on e.
    token_frac = jnp.mean(first_onehot, axis=0)        # [E]
    prob_frac = jnp.mean(probs, axis=0)                # [E]
    aux = jnp.sum(token_frac * prob_frac) * E
    return dispatch, combine, aux, z_loss


def moe_ffn(x, params, cfg: MoEConfig,
            rng: Optional[jax.Array] = None
            ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Apply the MoE FFN to per-rank tokens ``x [S, D]``.

    Inside shard_map with ``ep`` bound, ``params["w1"]/["w2"]`` are the
    LOCAL expert slab [E/ep, D, F] and the dispatch/return exchanges ride
    two ``lax.all_to_all``; without ``ep_axis`` every expert is local.
    Returns ``(y [S, D], aux_loss, z_loss)`` — dropped tokens yield zeros
    (callers add the residual).  ``rng`` is required iff
    ``cfg.router_noise > 0``.
    """
    S, D = x.shape
    E = cfg.n_experts
    C = cfg.capacity(S)
    dispatch, combine, aux, z_loss = _route(x, params["router"], cfg, rng)

    # [E, C, D] expert buffers (einsum dispatch — MXU, no scatter).
    buf = jnp.einsum("sec,sd->ecd", dispatch.astype(x.dtype), x)

    ep = compat_axis_size(cfg.ep_axis) if cfg.ep_axis else 1
    if ep > 1:
        if E % ep:
            raise ValueError(f"n_experts={E} must divide by ep={ep}")
        # Send each expert's buffer to its home rank; receive every rank's
        # buffers for OUR local experts, stacked along capacity:
        # [E, C, D] -> [E/ep, ep*C, D].
        buf = lax.all_to_all(buf, cfg.ep_axis, split_axis=0, concat_axis=1,
                             tiled=True)

    h = jax.nn.silu(jnp.einsum("ecd,edf->ecf", buf, params["w1"]))
    if cfg.gated:
        h = h * jnp.einsum("ecd,edf->ecf", buf, params["w3"])
    out = jnp.einsum("ecf,efd->ecd", h, params["w2"])

    if ep > 1:
        # Return trip: split the stacked capacity axis back per source
        # rank and send each chunk home -> [E, C, D] of OUR tokens'
        # outputs (chunk j went to rank j and comes back from rank j, so
        # expert-block order is preserved).
        out = lax.all_to_all(out, cfg.ep_axis, split_axis=1, concat_axis=0,
                             tiled=True)

    y = jnp.einsum("sec,ecd->sd", combine.astype(x.dtype), out)
    return y, aux.astype(jnp.float32), z_loss.astype(jnp.float32)


# ----------------------------------------------------------- tiny LM model
@dataclasses.dataclass(frozen=True)
class MoELMConfig:
    """Minimal MoE language model (embed → N × [attention-free mixer +
    MoE FFN] → head) — the test/bench vehicle for expert parallelism."""
    vocab_size: int = 256
    d_model: int = 64
    n_layers: int = 2
    moe: MoEConfig = dataclasses.field(default_factory=MoEConfig)
    aux_weight: float = 0.01
    dp_axis: Optional[str] = "dp"


def lm_init(cfg: MoELMConfig, key) -> Dict:
    keys = jax.random.split(key, 2 + cfg.n_layers)
    D = cfg.d_model
    return {
        "embed": (jax.random.normal(keys[0], (cfg.vocab_size, D),
                                    jnp.float32) / np.sqrt(D)).astype(
            cfg.moe.dtype),
        "layers": [init_params(cfg.moe, keys[1 + i])
                   for i in range(cfg.n_layers)],
        "head": (jax.random.normal(keys[-1], (D, cfg.vocab_size),
                                   jnp.float32) / np.sqrt(D)).astype(
            cfg.moe.dtype),
    }


def lm_param_specs(cfg: MoELMConfig) -> Dict:
    return {"embed": P(), "head": P(),
            "layers": [param_specs(cfg.moe) for _ in range(cfg.n_layers)]}


def lm_loss(params, tokens, targets, cfg: MoELMConfig,
            rng: Optional[jax.Array] = None):
    """Per-rank partial mean loss (same sum-semantics convention as
    models/llama.py): scaled so psum over dp AND ep recovers the global
    mean — ep is a DATA split here (GShard-style: every (dp, ep)
    coordinate routes its own token shard; only experts live on ep).

    ``rng`` threads router jitter (cfg.moe.router_noise): folded per
    layer AND per data-axis coordinate, so every (dp, ep) rank draws
    independent noise over its own token shard while redundant compute
    (none here) would stay deterministic.
    """
    B, T = tokens.shape
    x = params["embed"][tokens].reshape(B * T, -1)
    if rng is not None:
        for ax in (cfg.dp_axis, cfg.moe.ep_axis):
            if ax:
                rng = jax.random.fold_in(rng, lax.axis_index(ax))
    aux_total = 0.0
    z_total = 0.0
    for i, lp in enumerate(params["layers"]):
        layer_rng = (jax.random.fold_in(rng, i)
                     if rng is not None else None)
        y, aux, zl = moe_ffn(x, lp, cfg.moe, rng=layer_rng)
        x = x + y
        aux_total = aux_total + aux
        z_total = z_total + zl
    logits = (x @ params["head"]).astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets.reshape(-1)[:, None],
                               axis=-1)[:, 0]
    denom = float(nll.size)
    for ax in (cfg.dp_axis, cfg.moe.ep_axis):
        if ax:
            denom = denom * compat_axis_size(ax)
    router_losses = (cfg.aux_weight * aux_total
                     + cfg.moe.router_z_weight * z_total)
    return (jnp.sum(nll) + router_losses * float(nll.size)) / denom


def lm_sync_grads(grads, cfg: MoELMConfig):
    """psum over dp for everything; over ep only for ep-REPLICATED leaves
    (router/embed/head) — expert slabs are exact per rank (each rank
    computed its own experts' full gradient)."""
    specs = lm_param_specs(cfg)

    def leaf(g, spec):
        if cfg.dp_axis:
            g = lax.psum(g, cfg.dp_axis)
        ep = cfg.moe.ep_axis
        if ep and all(s != ep for s in spec):
            g = lax.psum(g, ep)
        return g

    return jax.tree_util.tree_map(leaf, grads, specs,
                                  is_leaf=lambda s: isinstance(s, P))


def make_train_step(cfg: MoELMConfig, optimizer, with_rng: bool = False):
    """Train step; ``with_rng=True`` adds a trailing ``rng`` argument that
    threads router jitter into ``lm_loss`` (required when
    cfg.moe.router_noise > 0)."""
    import optax

    def _step(params, opt_state, tokens, targets, rng):
        loss_p, grads = jax.value_and_grad(lm_loss)(params, tokens,
                                                    targets, cfg, rng)
        grads = lm_sync_grads(grads, cfg)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        for ax in (cfg.dp_axis, cfg.moe.ep_axis):
            if ax:
                loss_p = lax.psum(loss_p, ax)
        return params, opt_state, loss_p

    if with_rng:
        return _step

    def step(params, opt_state, tokens, targets):
        return _step(params, opt_state, tokens, targets, None)

    return step
