"""Llama-family decoder with explicit dp/tp/sp parallelism (flagship model).

Role in the rebuild: BASELINE config #4 ("Llama-3 8B pure-DP with Adasum /
hierarchical allreduce on torus") plus the long-context requirement the
reference lacks (SURVEY.md §5): ring attention over the ``sp`` axis, Megatron
tensor parallelism over ``tp``, gradient allreduce over ``dp`` — all written
as explicit SPMD for ``shard_map``, the TPU-native analogue of the
reference's explicit-collective style (vs. letting GSPMD guess).

Parameters are plain pytrees (dict of dicts of jnp arrays) with a parallel
tree of ``PartitionSpec``s (``param_specs``) describing how each leaf is
sharded over the mesh; activations: batch over ``dp``, sequence over ``sp``,
heads/ffn over ``tp``.

TP convention (Megatron): wq/wk/wv/w1/w3 column-sharded, wo/w2 row-sharded
with a psum after; norms/embeddings replicated (their grads are psum'd over
``tp`` in the train step — the f/g-operator pair).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P
from ..compat import axis_size as compat_axis_size

from ..parallel.ring_attention import (NEG_INF, local_flash_attention,
                                       ring_attention)


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    d_ff: int = 14336
    max_seq: int = 8192
    rope_theta: float = 500000.0
    dtype: Any = jnp.bfloat16
    # mesh axis names (set to None to disable an axis)
    dp_axis: Optional[str] = "dp"
    tp_axis: Optional[str] = "tp"
    sp_axis: Optional[str] = "sp"
    # Sequence-parallel engine: "ring" (K/V rotate — any head count,
    # O(T/sp) memory) or "ulysses" (two alltoalls to head-sharded layout —
    # needs q AND kv heads per tp shard divisible by sp; wins when ICI
    # alltoall bandwidth is plentiful).
    sp_impl: str = "ring"
    # Pipeline parallelism (beyond-ref, SURVEY.md §2c PP row): stage =
    # contiguous layer slab.  When set, ``init_params``/``param_specs``
    # emit the layer stack as STACKED arrays [n_layers, ...] sharded over
    # ``pp_axis`` (shard_map hands each stage its slab in layer order) and
    # ``forward`` runs the GPipe schedule from parallel/pipeline.py.
    # Composes with dp (data split) / tp (params within a layer) / sp
    # (sequence within attention).
    pp_axis: Optional[str] = None
    # Microbatches for the pipeline fill/drain (bubble = (pp-1)/(pp+M-1));
    # the per-shard batch must divide by it.  Ignored without pp_axis.
    n_microbatches: int = 2
    # Rematerialize each pipeline stage's forward in the backward scan
    # (jax.checkpoint): activation memory stops scaling with stage depth —
    # the 1F1B memory dividend, XLA-style (see parallel/pipeline.py).
    remat_stages: bool = False
    # Rematerialize each transformer layer in the NON-pipelined forward:
    # activation memory per layer collapses to the layer input, at ~1/3
    # extra forward FLOPs.  The measured lever for the large-batch HBM
    # falloff (docs/benchmarks.md "Llama batch scaling"): per-chip
    # throughput decays past B=16 at T=512 without it.
    remat_layers: bool = False
    # Where the LM loss is computed under pp (docs/parallelism.md):
    # "broadcast"  — psum the [M, mb, T, D] pipeline output to every
    #                stage; each computes final-norm+head+nll redundantly
    #                (1/pp-scaled).  Simple; costs one activation psum
    #                (~M·mb·T·D bytes/step over the pp axis) plus
    #                redundant [B,T,vocab] matmuls.
    # "last_stage" — no activation broadcast: only the final stage's
    #                output is real (zeros elsewhere); every stage still
    #                runs the head matmul in lockstep (SPMD — no wall
    #                saving there) but only the last stage's nll counts
    #                and ONLY the scalar loss rides the psum.  At 8B
    #                geometry the avoided broadcast is ~B·T·4096·2 bytes
    #                per step per pp hop.  forward()/logits are then only
    #                valid on the last stage.
    pp_loss: str = "broadcast"
    # Mixture-of-Experts MLP (models/moe.py): n_experts > 0 replaces the
    # dense w1/w3/w2 MLP with Switch-routed experts; ``ep_axis`` shards
    # them (a DATA axis for everything else — tokens split over dp×ep, so
    # shard the batch over ("dp", "ep")).  Composes with tp (attention
    # stays tp-sharded; experts are not additionally tp-split), sp, and
    # pp (the router aux loss rides the pipeline carry as per-stage
    # partials).
    n_experts: int = 0
    ep_axis: Optional[str] = None
    capacity_factor: float = 1.25
    aux_weight: float = 0.01           # router load-balance loss weight
    router_mode: str = "tokens"        # "tokens" | "expert_choice"
    router_top_k: int = 1              # 1 = Switch, >=2 = GShard top-k
    router_z_weight: float = 0.0       # ST-MoE z-loss weight (0 = off)
    router_noise: float = 0.0          # router jitter std (needs rng=)
    moe_gated: bool = False            # SwiGLU experts (Mixtral shape)
    # Pallas flash attention: True/False, or None = resolve from the
    # HVD_TPU_FLASH env var at TRACE time (auto: on TPU for sequences at
    # or past the measured crossover HVD_TPU_FLASH_MIN_SEQ — causal
    # default 512; below it XLA's fused attention is faster, see
    # ops/flash_attention.flash_min_seq).  The env vars are not part of
    # any jit cache key — to toggle after a step has compiled, change
    # this config field (it IS traced).
    use_flash: Optional[bool] = None
    # Sliding-window (Mistral-style) causal attention: each position
    # attends its last ``sliding_window`` positions only.  The flash
    # kernel skips whole out-of-window blocks (O(T·W) compute); local
    # attention only for now — sp (ring/Ulysses) rejects it at trace
    # time (ring-step skipping is the natural extension).
    sliding_window: Optional[int] = None
    # Rolling KV cache for windowed decode: the cache becomes a ring of
    # ``sliding_window + rolling_slack`` slots (position p lives at slot
    # p mod R) instead of max_seq — O(W) serving memory and UNBOUNDED
    # generation length.  The slack keeps a chunked write (decode_chunk,
    # speculative verify) from overwriting slots its own earlier rows
    # still attend: any chunk up to ``rolling_slack`` tokens is safe.
    rolling_cache: bool = False
    rolling_slack: int = 8
    # RMSNorm epsilon — checkpoint-dependent (Llama-3: 1e-5; several
    # families use 1e-6); models/convert.py parity depends on matching
    # the source checkpoint's value.
    norm_eps: float = 1e-5

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    def __post_init__(self):
        if self.sp_impl not in ("ring", "ulysses"):
            raise ValueError(
                f"sp_impl must be 'ring' or 'ulysses', got "
                f"{self.sp_impl!r}")
        if self.pp_loss not in ("broadcast", "last_stage"):
            raise ValueError(
                f"pp_loss must be 'broadcast' or 'last_stage', got "
                f"{self.pp_loss!r}")
        if self.sliding_window is not None and self.sliding_window < 1:
            raise ValueError(
                f"sliding_window must be >= 1 (or None to disable), got "
                f"{self.sliding_window!r}")
        if self.rolling_cache:
            if not self.sliding_window:
                raise ValueError("rolling_cache requires sliding_window "
                                 "(a full-attention model needs every "
                                 "past position)")
            if self.rolling_slack < 1:
                raise ValueError("rolling_slack must be >= 1")

    @property
    def all_axes(self):
        """Every mesh axis this model can touch — THE axis list for loss
        scaling and loss psums (one place to extend, three consumers)."""
        return (self.dp_axis, self.sp_axis, self.tp_axis, self.pp_axis,
                self.ep_axis)

    @property
    def spec_gated_axes(self):
        """Axes whose gradient psum is per-leaf spec-gated: leaves SHARDED
        over the axis carry exact shard gradients (no psum); replicated
        leaves' partials are summed.  tp/pp = redundant compute; ep = a
        data axis whose expert slabs already aggregated every rank's
        tokens through the all_to_all transpose."""
        return (self.tp_axis, self.pp_axis, self.ep_axis)

    def moe_cfg(self):
        """The models.moe config for this model's MoE MLP (single source
        of truth: init/specs/forward all derive from moe.py through it)."""
        from . import moe as _moe
        return _moe.MoEConfig(
            d_model=self.d_model, d_ff=self.d_ff,
            n_experts=self.n_experts, capacity_factor=self.capacity_factor,
            ep_axis=self.ep_axis, router_mode=self.router_mode,
            router_top_k=self.router_top_k,
            router_z_weight=self.router_z_weight,
            router_noise=self.router_noise, gated=self.moe_gated,
            dtype=self.dtype)


def tiny(vocab_size: int = 256, d_model: int = 64, n_layers: int = 2,
         n_heads: int = 4, n_kv_heads: int = 2, d_ff: int = 128,
         max_seq: int = 128, **kw) -> LlamaConfig:
    """Small config for tests / dryruns."""
    return LlamaConfig(vocab_size=vocab_size, d_model=d_model,
                       n_layers=n_layers, n_heads=n_heads,
                       n_kv_heads=n_kv_heads, d_ff=d_ff, max_seq=max_seq, **kw)


def llama3_8b() -> LlamaConfig:
    return LlamaConfig()  # defaults above are the 8B geometry


def mixtral_8x7b() -> LlamaConfig:
    """Mixtral-8x7B geometry: Mistral attention + 8 SwiGLU experts with
    normalized top-2 routing (models/moe.py gated experts).

    ``capacity_factor=4.0`` (= n_experts / top_k) gives every expert
    worst-case capacity, so NO token is ever capacity-dropped and a
    converted checkpoint reproduces HF logits exactly (Mixtral itself
    has no capacity drops).  Training at scale usually wants a tighter
    factor (1.25–2.0) — override ``capacity_factor`` for that; drops
    then fall back to the residual path."""
    return LlamaConfig(vocab_size=32000, d_model=4096, n_layers=32,
                       n_heads=32, n_kv_heads=8, d_ff=14336,
                       max_seq=32768, rope_theta=1e6,
                       n_experts=8, router_top_k=2, moe_gated=True,
                       capacity_factor=4.0, ep_axis="ep")


def mistral_7b() -> LlamaConfig:
    """Mistral-7B geometry: the Llama architecture + sliding-window
    attention (the flash kernel skips whole out-of-window blocks)."""
    return LlamaConfig(vocab_size=32000, d_model=4096, n_layers=32,
                       n_heads=32, n_kv_heads=8, d_ff=14336,
                       max_seq=32768, rope_theta=10000.0,
                       sliding_window=4096)


# ------------------------------------------------------------------- params
def init_params(cfg: LlamaConfig, key) -> Dict:
    """Initialize the full (unsharded) parameter pytree."""
    k = iter(jax.random.split(key, 4 + 7 * cfg.n_layers))
    D, H, K, Hd, F = (cfg.d_model, cfg.n_heads, cfg.n_kv_heads,
                      cfg.head_dim, cfg.d_ff)
    dt = cfg.dtype

    def dense(key, fan_in, shape):
        return (jax.random.normal(key, shape, jnp.float32)
                * (1.0 / np.sqrt(fan_in))).astype(dt)

    layers = []
    for _ in range(cfg.n_layers):
        layer = {
            "attn_norm": jnp.ones((D,), dt),
            "wq": dense(next(k), D, (D, H * Hd)),
            "wk": dense(next(k), D, (D, K * Hd)),
            "wv": dense(next(k), D, (D, K * Hd)),
            "wo": dense(next(k), H * Hd, (H * Hd, D)),
            "mlp_norm": jnp.ones((D,), dt),
        }
        if cfg.n_experts:
            from . import moe as _moe
            layer["moe"] = _moe.init_params(cfg.moe_cfg(), next(k))
        else:
            layer |= {
                "w1": dense(next(k), D, (D, F)),
                "w3": dense(next(k), D, (D, F)),
                "w2": dense(next(k), F, (F, D)),
            }
        layers.append(layer)
    if cfg.pp_axis:
        # Stacked layout [n_layers, ...]: shard_map slices axis 0 over the
        # pp axis in order, so stage i holds the contiguous layer slab
        # [i*L/pp, (i+1)*L/pp).  tree_map so nested subtrees (MoE) stack.
        layers = jax.tree_util.tree_map(lambda *ls: jnp.stack(ls), *layers)
    return {
        "embed": dense(next(k), D, (cfg.vocab_size, D)),
        "layers": layers,
        "final_norm": jnp.ones((D,), dt),
        "lm_head": dense(next(k), D, (D, cfg.vocab_size)),
    }


def param_specs(cfg: LlamaConfig) -> Dict:
    """PartitionSpec tree matching ``init_params`` (tp shards within a
    layer, pp shards the stacked layer axis; params are replicated over
    dp/sp)."""
    tp = cfg.tp_axis
    layer = {
        "attn_norm": P(),
        "wq": P(None, tp),
        "wk": P(None, tp),
        "wv": P(None, tp),
        "wo": P(tp, None),
        "mlp_norm": P(),
    }
    if cfg.n_experts:
        from . import moe as _moe
        layer["moe"] = _moe.param_specs(cfg.moe_cfg())
    else:
        layer |= {
            "w1": P(None, tp),
            "w3": P(None, tp),
            "w2": P(tp, None),
        }
    if cfg.pp_axis:
        layers = jax.tree_util.tree_map(
            lambda spec: P(cfg.pp_axis, *spec), layer,
            is_leaf=lambda x: isinstance(x, P))
    else:
        layers = [jax.tree_util.tree_map(
            lambda s: s, layer, is_leaf=lambda x: isinstance(x, P))
            for _ in range(cfg.n_layers)]
    return {
        "embed": P(),
        "layers": layers,
        "final_norm": P(),
        "lm_head": P(),
    }


# ------------------------------------------------------------------ forward
def _rmsnorm(x, w, eps=1e-5):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x.astype(jnp.float32) * lax.rsqrt(var + eps)).astype(x.dtype) * w


def _rope(x, positions, theta):
    """Rotary embeddings; x: [B, T, H, Hd], positions: [T]."""
    B, T, H, Hd = x.shape
    half = Hd // 2
    freqs = 1.0 / (theta ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    angles = positions[:, None].astype(jnp.float32) * freqs[None, :]  # [T, half]
    cos = jnp.cos(angles)[None, :, None, :]
    sin = jnp.sin(angles)[None, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    xf1, xf2 = x1.astype(jnp.float32), x2.astype(jnp.float32)
    return jnp.concatenate(
        [xf1 * cos - xf2 * sin, xf2 * cos + xf1 * sin], axis=-1).astype(x.dtype)


def _use_pallas_flash(cfg: "LlamaConfig", seq: Optional[int] = None) -> bool:
    """Pallas flash attention on TPU by default for sequences past the
    measured crossover (the [Tq,Tk] scores never touch HBM —
    ops/flash_attention.py; below it XLA's fused attention is faster,
    see flash_min_seq).  ``cfg.use_flash`` decides when set; otherwise
    HVD_TPU_FLASH=1/0 forces it on (interpret mode off-TPU, for tests)
    or off — read at TRACE time only (see LlamaConfig)."""
    from ..ops.flash_attention import resolve_flash
    return resolve_flash(cfg.use_flash, seq=seq, causal=True)


def _qkv(x, p, cfg: LlamaConfig, positions):
    """Project + rope this rank's head shard — THE qkv contract, shared
    by training attention, blockwise prefill and decode_step so the
    three paths cannot drift (tp head split, rope on q and k)."""
    B, T, _ = x.shape
    tp = compat_axis_size(cfg.tp_axis) if cfg.tp_axis else 1
    if cfg.n_heads % tp or cfg.n_kv_heads % tp:
        raise ValueError(f"n_heads={cfg.n_heads}/n_kv_heads={cfg.n_kv_heads} "
                         f"must be divisible by tp={tp}")
    H, K, Hd = cfg.n_heads // tp, cfg.n_kv_heads // tp, cfg.head_dim
    q = (x @ p["wq"]).reshape(B, T, H, Hd)
    k = (x @ p["wk"]).reshape(B, T, K, Hd)
    v = (x @ p["wv"]).reshape(B, T, K, Hd)
    return (_rope(q, positions, cfg.rope_theta),
            _rope(k, positions, cfg.rope_theta), v)


def _wo_project(out, p, cfg: LlamaConfig):
    """Row-parallel output projection (+psum over tp) — shared epilogue
    of every attention path."""
    B, T = out.shape[:2]
    o = out.reshape(B, T, -1) @ p["wo"]
    if cfg.tp_axis:
        o = lax.psum(o, cfg.tp_axis)
    return o


def _local_attend(q, k, v, cfg: LlamaConfig):
    """Causal local attention through the same flash routing as every
    path (Pallas kernel on TPU, jnp fallback otherwise); sliding window
    when the config asks for it."""
    if _use_pallas_flash(cfg, seq=q.shape[1]):
        from ..ops.flash_attention import flash_attention
        return flash_attention(q, k, v, causal=True,
                               window=cfg.sliding_window)
    return local_flash_attention(q, k, v, causal=True,
                                 window=cfg.sliding_window)


def _attention(x, p, cfg: LlamaConfig, positions):
    """Self-attention on the local tp shard of heads; sp-ring over sequence."""
    q, kk, v = _qkv(x, p, cfg, positions)

    sp = compat_axis_size(cfg.sp_axis) if cfg.sp_axis else 1
    if sp > 1 and cfg.sliding_window:
        raise ValueError(
            "sliding_window composes with dp/tp/pp/ep but not (yet) with "
            "sequence parallelism — disable sp_axis or the window")
    if sp > 1 and cfg.sp_impl == "ulysses":
        # Head exchange instead of kv rotation (docs/parallelism.md for
        # the tradeoff); GQA kv travels un-repeated through the alltoall.
        from ..ops.flash_attention import flash_attention
        from ..parallel.ulysses import ulysses_attention
        # Ulysses attends the FULL gathered sequence on local heads.
        attn = (flash_attention if _use_pallas_flash(cfg, seq=q.shape[1] * sp)
                else local_flash_attention)   # same routing as every path
        out = ulysses_attention(q, kk, v, attn_fn=attn,
                                axis_name=cfg.sp_axis, causal=True)
    elif sp > 1:
        # GQA passes through un-repeated: the ring handles it on both
        # engines (pallas reads shared kv heads through block index maps —
        # H/K× less ring traffic; the jnp fallback repeats internally).
        out = ring_attention(q, kk, v, axis_name=cfg.sp_axis, causal=True,
                             use_flash=cfg.use_flash)
    else:
        out = _local_attend(q, kk, v, cfg)
    return _wo_project(out, p, cfg)


def _mlp(x, p, cfg: LlamaConfig, rng=None):
    """Dense SwiGLU MLP, or top-k-routed MoE when cfg.n_experts > 0.

    Returns ``(y, router_losses [2])`` — ``[aux, z_loss]`` stacked so ONE
    scalar-shaped carrier threads both through scans/pipeline carries;
    dense returns zeros.  The MoE path is NOT tp-split (experts shard
    over ep; every tp rank computes the same routing/experts redundantly
    — acceptable at the tp degrees attention wants, and it keeps the
    exchange one all_to_all instead of a tp×ep lattice; the arithmetic
    is written down in docs/moe.md)."""
    if cfg.n_experts:
        from . import moe as _moe
        B, T, D = x.shape
        y, aux, zl = _moe.moe_ffn(x.reshape(B * T, D), p["moe"],
                                  cfg.moe_cfg(), rng=rng)
        return y.reshape(B, T, D), jnp.stack([aux, zl])
    h = jax.nn.silu(x @ p["w1"]) * (x @ p["w3"])
    out = h @ p["w2"]
    if cfg.tp_axis:
        out = lax.psum(out, cfg.tp_axis)
    return out, jnp.zeros((2,), jnp.float32)


def _layer_apply(p, x, cfg: LlamaConfig, positions, rng=None):
    x = x + _attention(_rmsnorm(x, p["attn_norm"], cfg.norm_eps), p, cfg,
                       positions)
    y, aux = _mlp(_rmsnorm(x, p["mlp_norm"], cfg.norm_eps), p, cfg,
                  rng=rng)
    return x + y, aux


def forward(params, tokens, cfg: LlamaConfig, rng=None):
    """Logits for local token shard (public surface; see _forward)."""
    return _forward(params, tokens, cfg, rng=rng)[0]


def _forward(params, tokens, cfg: LlamaConfig, rng=None):
    """(logits, router_losses [2]) for local token shard [B_loc, T_loc]
    (call inside shard_map, or directly when all axes are disabled/
    size-1).  ``router_losses`` stacks the summed MoE load-balance aux
    and router z-loss (zeros for dense models).

    ``rng`` (router jitter) is folded once with every DATA axis index
    (dp/ep/sp — each rank draws independent noise over its own token
    shard; tp/pp ranks computing the same routing redundantly share the
    draw) and then per layer.  Under pp, microbatches within a stage
    share a layer's draw — jitter is a regularizer, not a statistical
    contract, so the correlation is accepted.

    With ``pp_axis`` set, ``params["layers"]`` is this stage's slab of the
    stacked layer arrays and the blocks run under the GPipe microbatch
    schedule; embedding and the LM head are computed replicated on every
    stage (cheap next to the blocks), with the head reading the last
    stage's pipeline output broadcast via the zero-sum psum trick."""
    B, T = tokens.shape
    if cfg.sp_axis:
        sp_idx = lax.axis_index(cfg.sp_axis)
        positions = sp_idx * T + jnp.arange(T)
    else:
        positions = jnp.arange(T)
    if rng is not None:
        for ax in (cfg.dp_axis, cfg.ep_axis, cfg.sp_axis):
            if ax:
                rng = jax.random.fold_in(rng, lax.axis_index(ax))
    x = params["embed"][tokens]
    aux_total = jnp.zeros((2,), jnp.float32)
    if cfg.pp_axis:
        from ..parallel.pipeline import microbatch, pipeline_apply
        M = cfg.n_microbatches
        micro_x = microbatch(x, M)           # [M, B/M, T, D]

        def stage_fn(slab, xm):
            lps = jax.tree_util.tree_leaves(slab)[0].shape[0]
            base = (lax.axis_index(cfg.pp_axis) * lps
                    if rng is not None else 0)

            def body(carry, p):
                h, aux, j = carry
                lrng = (jax.random.fold_in(rng, base + j)
                        if rng is not None else None)
                h, a = _layer_apply(p, h, cfg, positions, rng=lrng)
                return (h, aux + a, j + 1), None
            (h, aux, _), _ = lax.scan(
                body, (xm, jnp.zeros((2,), jnp.float32),
                       jnp.zeros((), jnp.int32)), slab)
            return h, aux

        x, aux_total = pipeline_apply(
            stage_fn, params["layers"], micro_x, axis_name=cfg.pp_axis,
            broadcast_out=(cfg.pp_loss == "broadcast"),
            remat=cfg.remat_stages, with_aux=True,
            aux_init=aux_total)
        # moe aux/z are per-token MEANs (batch-size invariant); the
        # pipeline accumulated one per microbatch, so average — otherwise
        # the scheduling knob n_microbatches would scale the training
        # objective.
        aux_total = aux_total / M
        x = x.reshape((B, T, -1))
    else:
        def _apply(p, h, positions, lrng):
            return _layer_apply(p, h, cfg, positions, rng=lrng)
        if cfg.remat_layers:
            _apply = jax.checkpoint(_apply)
        for i, p in enumerate(params["layers"]):
            lrng = (jax.random.fold_in(rng, i)
                    if rng is not None else None)
            x, aux = _apply(p, x, positions, lrng)
            aux_total = aux_total + aux
    x = _rmsnorm(x, params["final_norm"], cfg.norm_eps)
    return x @ params["lm_head"], aux_total


def loss_fn(params, tokens, targets, cfg: LlamaConfig, rng=None):
    """PARTIAL next-token cross-entropy: this rank's contribution to the
    global mean.  ``rng`` threads router jitter (cfg.router_noise > 0
    requires it; see _forward for the fold-in contract).

    Written for shard_map's sum-semantics autodiff (the transpose of an
    in-graph psum is psum): the differentiated function contains NO loss
    psum; instead per-rank partial losses are scaled so they sum to the true
    global mean across every mesh axis — 1/(global_count) for the dp/sp data
    split and 1/tp for the redundant tensor-parallel compute.  ``sync_grads``
    then turns per-rank partial grads into the exact mean gradient, and
    ``psum_loss`` recovers the scalar for logging.
    """
    logits, router = _forward(params, tokens, cfg, rng=rng)
    logits = logits.astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    # dp/sp/ep factors extend the local count to the global token count
    # (ep is a data axis when MoE is on); the tp/pp factors split the
    # redundantly-computed loss across ranks (every tp rank computes the
    # full head; every pp stage computes the loss from the broadcast
    # pipeline output).
    denom = float(nll.size)
    axes_denom = 1.0
    for ax in cfg.all_axes:
        if ax:
            axes_denom = axes_denom * compat_axis_size(ax)
    nll_sum = jnp.sum(nll)
    if cfg.pp_axis and cfg.pp_loss == "last_stage":
        # Only the final stage's pipeline output is real (no activation
        # broadcast); mask the garbage nll elsewhere and undo pp's share
        # of the redundancy factor — the loss is no longer computed pp×
        # redundantly, it exists once.
        pp_n = compat_axis_size(cfg.pp_axis)
        is_last = (lax.axis_index(cfg.pp_axis) == pp_n - 1)
        nll_sum = jnp.where(is_last, nll_sum, 0.0) * pp_n
    total = nll_sum / (denom * axes_denom)
    if cfg.n_experts:
        # Per-rank mean router losses (mean over layers), scaled so the
        # psum over every axis yields the cross-rank mean.  Unlike the
        # nll (redundant over pp via the broadcast output), they are
        # PARTITIONED over pp — each stage computed only its own slab's
        # routers — so pp's factor must not divide them.
        aux_denom = axes_denom
        if cfg.pp_axis:
            aux_denom = aux_denom / compat_axis_size(cfg.pp_axis)
        router_losses = (cfg.aux_weight * router[0]
                         + cfg.router_z_weight * router[1])
        total = total + (router_losses / cfg.n_layers) / aux_denom
    return total


def psum_loss(loss_partial, cfg: LlamaConfig):
    """Sum per-rank partial losses into the true global mean loss."""
    for ax in cfg.all_axes:
        if ax:
            loss_partial = lax.psum(loss_partial, ax)
    return loss_partial


# --------------------------------------------------------------- train step
def sync_grads(grads, cfg: LlamaConfig, specs=None):
    """Cross-rank gradient synchronization for the explicit-SPMD step.

    Under sum-semantics autodiff each rank's grad is its partial
    contribution, so:

    - ALL params: psum over dp (the Horovod allreduce) and sp (each sp rank
      saw a different sequence chunk).
    - tp-replicated params only (norms, embed, lm_head): additionally psum
      over tp to combine the per-shard contributions; tp-SHARDED params'
      grads are already exact for their shard (the cotangent arriving
      through the row-parallel psum's transpose is the full one).
    - pp-replicated params (embed/lm_head/final_norm): psum over pp — the
      embed grad is nonzero only on stage 0 (the pipeline consumes input
      there) and the head grad is 1/pp-scaled on every stage, so the psum
      reassembles both.  pp-SHARDED slabs are exact per stage, like tp.
    - ep (MoE): a data axis — non-expert leaves saw only this rank's
      token shard (psum over ep like dp/sp), while ep-SHARDED expert
      slabs already aggregated every ep rank's tokens through the
      all_to_all transpose (exact, no psum).
    The 1/(count·tp·pp·ep) scaling inside ``loss_fn`` makes these psums
    land on the exact global-mean gradient.
    """
    specs = specs or param_specs(cfg)
    gated = cfg.spec_gated_axes

    def leaf_sync(g, spec):
        for ax in (cfg.dp_axis, cfg.sp_axis):
            if ax:
                g = lax.psum(g, ax)
        for ax in gated:
            if ax and all(s != ax for s in spec):
                g = lax.psum(g, ax)
        return g

    return jax.tree_util.tree_map(leaf_sync, grads, specs,
                                  is_leaf=lambda x: isinstance(x, P))


# ---------------------------------------------------------------- inference
def init_cache(cfg: LlamaConfig, batch: int, max_seq: Optional[int] = None,
               sharded: Optional[bool] = None):
    """Per-layer KV cache ``[B, max_seq, n_kv_heads, head_dim]`` (zeros).

    Beyond-reference: Horovod ships no inference path at all; this is the
    decode half of the flagship model.  Static shape — the cache is a
    fixed ring of ``max_seq`` slots written via dynamic_update_slice, so
    one compiled decode step serves every position.
    """
    if cfg.rolling_cache:
        # Ring of W + slack slots (position p -> slot p mod R): O(W)
        # memory, unbounded generation.  max_seq is irrelevant here.
        T = cfg.sliding_window + cfg.rolling_slack
    else:
        T = max_seq or cfg.max_seq
    K = cfg.n_kv_heads
    if cfg.tp_axis:
        # Inside shard_map (tp decode) each rank holds its K/tp kv-head
        # shard; outside, the cache is global — shard it with
        # ``cache_specs``.  ``sharded`` overrides the auto-detection
        # (which keys on the axis name being bound at trace time).
        if sharded is None:
            try:
                tp = compat_axis_size(cfg.tp_axis)
            except NameError:       # axis unbound → outside shard_map
                tp = 1
        else:
            tp = compat_axis_size(cfg.tp_axis) if sharded else 1
        if cfg.n_kv_heads % tp:
            raise ValueError(f"n_kv_heads={cfg.n_kv_heads} must divide "
                             f"by tp={tp} for the sharded cache")
        K //= tp
    shape = (batch, T, K, cfg.head_dim)
    return [{"k": jnp.zeros(shape, cfg.dtype),
             "v": jnp.zeros(shape, cfg.dtype)}
            for _ in range(cfg.n_layers)]


def _check_cache_budget(t_final: int, cache_t: int,
                        cfg: Optional[LlamaConfig] = None):
    """Every position is static at trace time — refuse to decode past the
    cache instead of letting dynamic_update_slice clamp writes onto the
    last slot (which silently corrupts every later token).  A rolling
    cache has no length budget (positions wrap)."""
    if cfg is not None and cfg.rolling_cache:
        return
    if t_final > cache_t:
        raise ValueError(
            f"decode would write position {t_final - 1} but the KV cache "
            f"has only {cache_t} slots; raise max_seq (init_cache) or "
            f"generate fewer tokens")


def _decode_axes_check(cfg: LlamaConfig, what: str):
    """Decode supports tp (heads split, psum at wo — same Megatron
    contract as training) and rejects the training-only axes: dp is just
    batching (run more replicas), sp/pp restructure the sequence/depth in
    ways a token-at-a-time cache does not, ep would need the alltoall
    lattice per generated token."""
    bad = [ax for ax in (cfg.dp_axis, cfg.sp_axis, cfg.pp_axis,
                         cfg.ep_axis) if ax]
    if bad:
        raise ValueError(
            f"{what} supports tp only; disable {bad} "
            f"(dp/sp/pp/ep = None) in the decode config")


def decode_step(params, cache, tokens, pos, cfg: LlamaConfig):
    """One decode step: ``tokens [B]`` at position ``pos`` (traced
    scalar) -> (logits [B, vocab], updated cache).

    Runs single-device, or tp-sharded inside ``shard_map`` with the
    training param specs (wq/wk/wv column-split → this rank holds
    H/tp q heads and K/tp kv heads; wo row-split with a psum — the same
    f/g pair as ``_attention``) and the cache sharded over its head axis
    (``cache_specs``).  The Tq=1 case of ``decode_chunk`` — one
    implementation, two entry points.  Attention over the cache is a
    plain masked einsum: at Tq=1 there is no score matrix to tile, so
    flash buys nothing.
    """
    logits, cache = decode_chunk(params, cache, tokens[:, None], pos, cfg)
    return logits[:, 0, :], cache


def decode_chunk(params, cache, tokens, pos, cfg: LlamaConfig):
    """Cached forward over a SHORT chunk ``tokens [B, Tq]`` starting at
    position ``pos`` (traced scalar) -> (logits [B, Tq, vocab], cache).

    The multi-token generalization of ``decode_step`` (which is the
    Tq=1 case): chunk kv is written into the cache at [pos, pos+Tq) and
    each chunk row i attends the cache prefix ``<= pos + i`` — the
    verify pass of speculative decoding, and the building block for any
    multi-token stepping.  tp-sharded like decode_step.
    """
    _decode_axes_check(cfg, "decode_chunk")
    B, Tq = tokens.shape
    x = params["embed"][tokens]                      # [B, Tq, D]
    positions = pos + jnp.arange(Tq)
    new_cache = []
    T = cache[0]["k"].shape[1]
    if cfg.rolling_cache:
        if Tq > cfg.rolling_slack:
            raise ValueError(
                f"decode_chunk of {Tq} tokens exceeds rolling_slack="
                f"{cfg.rolling_slack}: earlier chunk rows would attend "
                f"slots the later writes just overwrote; raise "
                f"rolling_slack")
        # Slot j holds position p_j = the largest p ≤ (chunk end) with
        # p ≡ j (mod R); row i attends p_j in (pos+i-W, pos+i].  The
        # explicit p_j >= 0 term masks never-written slots — without it
        # a context SHORTER than the window would attend zero-filled
        # slots (their derived p_j is negative, but so is qpos-W then).
        R = T
        end = pos + Tq - 1
        j = jnp.arange(R)[None, :]
        p_j = end - ((end - j) % R)                  # [1, R]
        qpos = (pos + jnp.arange(Tq))[:, None]       # [Tq, 1]
        valid = (p_j >= 0) & (p_j <= qpos) \
            & (p_j > qpos - cfg.sliding_window)
        write_slots = (pos + jnp.arange(Tq)) % R     # [Tq]
    else:
        # valid[i, t]: chunk row i sees cache positions t <= pos + i
        # (and, with a sliding window, only the last W of them).
        valid = (jnp.arange(T)[None, :]
                 <= (pos + jnp.arange(Tq))[:, None])     # [Tq, T]
        if cfg.sliding_window:
            valid = jnp.logical_and(
                valid, jnp.arange(T)[None, :]
                > (pos + jnp.arange(Tq))[:, None] - cfg.sliding_window)
    valid = valid[None, None, None, :, :]            # [1,1,1,Tq,T]
    for p, c in zip(params["layers"], cache):
        h = _rmsnorm(x, p["attn_norm"], cfg.norm_eps)
        q, k_new, v_new = _qkv(h, p, cfg, positions)  # local head shard
        H, K, Hd = q.shape[2], k_new.shape[2], q.shape[3]
        if cfg.rolling_cache:
            if Tq == 1:
                # Hot decode loop: a single position is always a
                # contiguous write — dynamic_update_slice at pos % R
                # avoids scatter lowering per layer per token.
                ck = lax.dynamic_update_slice(
                    c["k"], k_new.astype(c["k"].dtype),
                    (0, pos % T, 0, 0))
                cv = lax.dynamic_update_slice(
                    c["v"], v_new.astype(c["v"].dtype),
                    (0, pos % T, 0, 0))
            else:
                ck = c["k"].at[:, write_slots].set(
                    k_new.astype(c["k"].dtype))
                cv = c["v"].at[:, write_slots].set(
                    v_new.astype(c["v"].dtype))
        else:
            ck = lax.dynamic_update_slice(
                c["k"], k_new.astype(c["k"].dtype), (0, pos, 0, 0))
            cv = lax.dynamic_update_slice(
                c["v"], v_new.astype(c["v"].dtype), (0, pos, 0, 0))
        new_cache.append({"k": ck, "v": cv})
        # GQA groups against the shared kv, one extra chunk axis q.
        qg = q.reshape(B, Tq, K, H // K, Hd)
        s = jnp.einsum("bqkrd,btkd->bkrqt", qg, ck,
                       preferred_element_type=jnp.float32)
        s = s / np.sqrt(Hd)
        s = jnp.where(valid, s, NEG_INF)
        w = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bkrqt,btkd->bqkrd", w.astype(cv.dtype), cv,
                       preferred_element_type=jnp.float32)
        x = x + _wo_project(o.reshape(B, Tq, H, Hd).astype(x.dtype),
                            p, cfg)
        y, _ = _mlp(_rmsnorm(x, p["mlp_norm"], cfg.norm_eps), p, cfg)
        x = x + y
    x = _rmsnorm(x, params["final_norm"], cfg.norm_eps)
    return (x @ params["lm_head"]).astype(jnp.float32), new_cache


def cache_specs(cfg: LlamaConfig):
    """PartitionSpecs for ``init_cache``'s pytree under tp decode: the
    kv-head axis shards over tp, matching the column-split wk/wv."""
    spec = {"k": P(None, None, cfg.tp_axis, None),
            "v": P(None, None, cfg.tp_axis, None)}
    return [spec for _ in range(cfg.n_layers)]


def prefill(params, cache, tokens, cfg: LlamaConfig):
    """Batched prefill: fill the cache from a prompt ``[B, T0]`` in ONE
    pass over the layers; returns (last logits, cache).

    Each layer projects q/k/v for the WHOLE prompt, writes its kv block
    into the cache at positions [0, T0), and attends causally through
    the same flash routing as training (Pallas kernel on TPU, tiled
    [Tq, Tk] scores that never materialize in HBM) — matmul-shaped MXU
    work, linear in prompt blocks.  The previous implementation scanned
    ``decode_step`` token-by-token: T0 sequential steps each attending
    over the full cache, O(T0·cache_T) with no batching (VERDICT r4
    weak #1).  tp-sharded like decode_step.
    """
    _decode_axes_check(cfg, "prefill")
    B, T0 = tokens.shape
    _check_cache_budget(T0, cache[0]["k"].shape[1], cfg)
    positions = jnp.arange(T0)
    x = params["embed"][tokens]                      # [B, T0, D]
    new_cache = []
    for p, c in zip(params["layers"], cache):
        h = _rmsnorm(x, p["attn_norm"], cfg.norm_eps)
        q, k, v = _qkv(h, p, cfg, positions)         # local head shard
        if cfg.rolling_cache:
            # Only the last min(T0, R) prompt positions can ever be
            # attended again — write just those, at their ring slots
            # (static indices: T0 and R are trace-time constants).
            R = c["k"].shape[1]
            keep = min(T0, R)
            slots = np.arange(T0 - keep, T0) % R
            ck = c["k"].at[:, slots].set(
                k[:, T0 - keep:].astype(c["k"].dtype))
            cv = c["v"].at[:, slots].set(
                v[:, T0 - keep:].astype(c["v"].dtype))
        else:
            ck = lax.dynamic_update_slice(c["k"], k.astype(c["k"].dtype),
                                          (0, 0, 0, 0))
            cv = lax.dynamic_update_slice(c["v"], v.astype(c["v"].dtype),
                                          (0, 0, 0, 0))
        new_cache.append({"k": ck, "v": cv})
        x = x + _wo_project(_local_attend(q, k, v, cfg), p, cfg)
        y, _ = _mlp(_rmsnorm(x, p["mlp_norm"], cfg.norm_eps), p, cfg)
        x = x + y
    x = _rmsnorm(x, params["final_norm"], cfg.norm_eps)
    return ((x[:, -1, :] @ params["lm_head"]).astype(jnp.float32),
            new_cache)


def sample_logits(logits, rng, temperature: float = 0.0,
                  top_p: float = 1.0, top_k: int = 0):
    """Pick next tokens from ``logits [B, vocab]``.

    temperature == 0 → greedy argmax (rng unused).  Otherwise scale by
    1/temperature, optionally keep only the ``top_k`` largest logits,
    optionally apply nucleus filtering (smallest set of tokens whose
    probability mass ≥ ``top_p``), then draw categorically.  All masks
    are static-shape (sort + where) — jit/scan friendly.
    """
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits.astype(jnp.float32) / temperature
    if top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, NEG_INF, logits)
    if top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # Keep every token strictly inside the nucleus plus the first one
        # past the boundary (standard nucleus semantics: the smallest set
        # reaching top_p).
        keep_sorted = cum - probs < top_p
        cutoff = jnp.min(jnp.where(keep_sorted, sorted_logits,
                                   jnp.inf), axis=-1)[:, None]
        logits = jnp.where(logits < cutoff, NEG_INF, logits)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


def generate(params, prompt, n_tokens: int, cfg: LlamaConfig,
             max_seq: Optional[int] = None,
             temperature: float = 0.0, top_p: float = 1.0,
             top_k: int = 0, rng=None):
    """Generation: ``prompt [B, T0]`` -> ``[B, n_tokens]``.

    Greedy by default; ``temperature > 0`` samples (with optional
    ``top_k`` / nucleus ``top_p`` filtering; ``rng`` required, folded
    per position).  jit-compatible end to end (scan over a static token
    budget); tp-sharded like decode_step — every tp rank holds the full
    psum'd logits, so sampling stays deterministic across the group as
    long as the caller passes the same rng to every rank."""
    B, T0 = prompt.shape
    if n_tokens < 1:
        return jnp.zeros((B, 0), jnp.int32)
    if temperature > 0.0 and rng is None:
        raise ValueError("temperature > 0 requires rng=")
    cache = init_cache(cfg, B, max_seq)
    # The last generated token's own kv is never written back, hence -1.
    _check_cache_budget(T0 + n_tokens - 1, cache[0]["k"].shape[1], cfg)
    logits, cache = prefill(params, cache, prompt, cfg)

    def pick(logits, t):
        step_rng = (jax.random.fold_in(rng, t)
                    if rng is not None else None)
        return sample_logits(logits, step_rng, temperature, top_p, top_k)

    def body(carry, t):
        tok, cache = carry
        logits, cache = decode_step(params, cache, tok, t, cfg)
        nxt = pick(logits, t)
        return (nxt, cache), nxt

    first = pick(logits, T0 - 1)
    (_, _), rest = lax.scan(body, (first, cache),
                            jnp.arange(T0, T0 + n_tokens - 1))
    return jnp.concatenate([first[:, None], rest.T], axis=1)


def speculative_generate(params, draft_params, prompt, n_tokens: int,
                         cfg: LlamaConfig,
                         draft_cfg: Optional[LlamaConfig] = None,
                         n_draft: int = 4,
                         max_seq: Optional[int] = None):
    """Greedy speculative decoding: a cheap draft model proposes
    ``n_draft`` tokens per round; the target model verifies them in ONE
    ``decode_chunk`` forward and emits every leading match plus the
    target's own correction token.

    EXACT by construction: the output equals greedy
    ``generate(params, prompt, n_tokens, cfg)`` token for token — the
    draft only changes how many sequential target forwards are needed
    (1 + n_accepted tokens per target forward instead of 1).  Batched:
    acceptance is the MINIMUM leading-match length across rows, so every
    row stays exact (for rows that matched further, the correction token
    IS their draft token); peak speedup needs agreeing rows.

    ``draft_cfg`` defaults to ``cfg`` (self-speculation layout); it must
    share the vocabulary.  jit-compatible end to end (``while_loop``
    over a static token budget; caches sized ``T0 + n_tokens + n_draft``
    so the last round's chunk always fits).
    """
    draft_cfg = draft_cfg or cfg
    _decode_axes_check(cfg, "speculative_generate")
    _decode_axes_check(draft_cfg, "speculative_generate (draft)")
    B, T0 = prompt.shape
    if n_tokens < 1:
        return jnp.zeros((B, 0), jnp.int32)
    k = int(n_draft)
    if k < 1:
        raise ValueError("n_draft must be >= 1")
    budget = max_seq or (T0 + n_tokens + k)
    cache_t = init_cache(cfg, B, budget)
    cache_d = init_cache(draft_cfg, B, budget)
    # Both caches have budgets of their own: a rolling target does not
    # exempt a fixed-length draft cache (whose clamped writes would
    # silently corrupt the draft and erode acceptance).
    _check_cache_budget(T0 + n_tokens + k, cache_t[0]["k"].shape[1], cfg)
    _check_cache_budget(T0 + n_tokens + k, cache_d[0]["k"].shape[1],
                        draft_cfg)

    logits_t, cache_t = prefill(params, cache_t, prompt, cfg)
    _, cache_d = prefill(draft_params, cache_d, prompt, draft_cfg)
    first = jnp.argmax(logits_t, axis=-1).astype(jnp.int32)   # [B]

    PAD = n_tokens + k + 1      # rounds overwrite their garbage tail
    out0 = jnp.zeros((B, PAD), jnp.int32)
    out0 = lax.dynamic_update_slice(out0, first[:, None], (0, 0))

    def cond(carry):
        return carry[1] < n_tokens

    def body(carry):
        out, n_done, last, cache_t, cache_d = carry
        p0 = T0 + n_done - 1    # position of `last`'s (unwritten) kv

        # Draft k tokens sequentially on the cheap model.  k+1 steps, not
        # k: the extra step writes d_k's own kv into the draft cache —
        # without it a fully-accepted round leaves a zero hole at
        # position p0+k that every later draft step would attend,
        # silently eroding the acceptance rate (output stays exact — the
        # target verifies — but the speedup decays).  Its proposed token
        # is discarded.
        def dstep(c, i):
            cache_d, tok = c
            logits, cache_d = decode_step(draft_params, cache_d, tok,
                                          p0 + i, draft_cfg)
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return (cache_d, nxt), nxt

        (cache_d, _), drafts = lax.scan(dstep, (cache_d, last),
                                        jnp.arange(k + 1))
        drafts = drafts.T[:, :k]                            # [B, k]

        # Verify in one target forward over [last, d_1..d_k]: logits row
        # i is the target's next-token distribution after position p0+i,
        # so t_i aligns with draft d_{i+1}.
        chunk = jnp.concatenate([last[:, None], drafts], axis=1)
        logits, cache_t = decode_chunk(params, cache_t, chunk, p0, cfg)
        targets = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B,k+1]

        m = (drafts == targets[:, :k])                      # [B, k]
        a_row = jnp.sum(jnp.cumprod(m.astype(jnp.int32), axis=1), axis=1)
        a = jnp.min(a_row)                                  # scalar 0..k
        correction = lax.dynamic_index_in_dim(targets, a, axis=1,
                                              keepdims=False)   # [B]
        padded = jnp.concatenate(
            [drafts, jnp.zeros((B, 1), jnp.int32)], axis=1)  # [B, k+1]
        emit = jnp.where(jnp.arange(k + 1)[None, :] < a, padded,
                         correction[:, None])
        out = lax.dynamic_update_slice(out, emit, (0, n_done))
        return out, n_done + a + 1, correction, cache_t, cache_d

    out, _, _, _, _ = lax.while_loop(
        cond, body, (out0, jnp.asarray(1, jnp.int32), first,
                     cache_t, cache_d))
    return out[:, :n_tokens]


def make_train_step(cfg: LlamaConfig, optimizer, with_rng: bool = False):
    """Returns ``step(params, opt_state, tokens, targets) -> (params,
    opt_state, loss)`` for use inside shard_map over (dp, sp, tp).
    ``with_rng=True`` adds a trailing ``rng`` argument threading router
    jitter (required when cfg.router_noise > 0)."""
    import optax

    def _step(params, opt_state, tokens, targets, rng):
        loss_partial, grads = jax.value_and_grad(loss_fn)(
            params, tokens, targets, cfg, rng)
        grads = sync_grads(grads, cfg)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, psum_loss(loss_partial, cfg)

    if with_rng:
        return _step

    def step(params, opt_state, tokens, targets):
        return _step(params, opt_state, tokens, targets, None)

    return step
