"""Llama-family decoder with explicit dp/tp/sp parallelism (flagship model).

Role in the rebuild: BASELINE config #4 ("Llama-3 8B pure-DP with Adasum /
hierarchical allreduce on torus") plus the long-context requirement the
reference lacks (SURVEY.md §5): ring attention over the ``sp`` axis, Megatron
tensor parallelism over ``tp``, gradient allreduce over ``dp`` — all written
as explicit SPMD for ``shard_map``, the TPU-native analogue of the
reference's explicit-collective style (vs. letting GSPMD guess).

Parameters are plain pytrees (dict of dicts of jnp arrays) with a parallel
tree of ``PartitionSpec``s (``param_specs``) describing how each leaf is
sharded over the mesh; activations: batch over ``dp``, sequence over ``sp``,
heads/ffn over ``tp``.

TP convention (Megatron): wq/wk/wv/w1/w3 column-sharded, wo/w2 row-sharded
with a psum after; norms/embeddings replicated (their grads are psum'd over
``tp`` in the train step — the f/g-operator pair).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ..parallel.ring_attention import (NEG_INF, local_flash_attention,
                                       ring_attention)


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    d_ff: int = 14336
    max_seq: int = 8192
    rope_theta: float = 500000.0
    dtype: Any = jnp.bfloat16
    # mesh axis names (set to None to disable an axis)
    dp_axis: Optional[str] = "dp"
    tp_axis: Optional[str] = "tp"
    sp_axis: Optional[str] = "sp"
    # Sequence-parallel engine: "ring" (K/V rotate — any head count,
    # O(T/sp) memory) or "ulysses" (two alltoalls to head-sharded layout —
    # needs q AND kv heads per tp shard divisible by sp; wins when ICI
    # alltoall bandwidth is plentiful).
    sp_impl: str = "ring"
    # Pipeline parallelism (beyond-ref, SURVEY.md §2c PP row): stage =
    # contiguous layer slab.  When set, ``init_params``/``param_specs``
    # emit the layer stack as STACKED arrays [n_layers, ...] sharded over
    # ``pp_axis`` (shard_map hands each stage its slab in layer order) and
    # ``forward`` runs the GPipe schedule from parallel/pipeline.py.
    # Composes with dp (data split) / tp (params within a layer) / sp
    # (sequence within attention).
    pp_axis: Optional[str] = None
    # Microbatches for the pipeline fill/drain (bubble = (pp-1)/(pp+M-1));
    # the per-shard batch must divide by it.  Ignored without pp_axis.
    n_microbatches: int = 2
    # Rematerialize each pipeline stage's forward in the backward scan
    # (jax.checkpoint): activation memory stops scaling with stage depth —
    # the 1F1B memory dividend, XLA-style (see parallel/pipeline.py).
    remat_stages: bool = False
    # Mixture-of-Experts MLP (models/moe.py): n_experts > 0 replaces the
    # dense w1/w3/w2 MLP with Switch-routed experts; ``ep_axis`` shards
    # them (a DATA axis for everything else — tokens split over dp×ep, so
    # shard the batch over ("dp", "ep")).  Composes with tp (attention
    # stays tp-sharded; experts are not additionally tp-split), sp, and
    # pp (the router aux loss rides the pipeline carry as per-stage
    # partials).
    n_experts: int = 0
    ep_axis: Optional[str] = None
    capacity_factor: float = 1.25
    aux_weight: float = 0.01           # router load-balance loss weight
    # Pallas flash attention: True/False, or None = resolve from the
    # HVD_TPU_FLASH env var at TRACE time (auto: on when running on TPU).
    # The env var is not part of any jit cache key — to toggle after a
    # step has compiled, change this config field (it IS traced).
    use_flash: Optional[bool] = None

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    def __post_init__(self):
        if self.sp_impl not in ("ring", "ulysses"):
            raise ValueError(
                f"sp_impl must be 'ring' or 'ulysses', got "
                f"{self.sp_impl!r}")

    @property
    def all_axes(self):
        """Every mesh axis this model can touch — THE axis list for loss
        scaling and loss psums (one place to extend, three consumers)."""
        return (self.dp_axis, self.sp_axis, self.tp_axis, self.pp_axis,
                self.ep_axis)

    @property
    def spec_gated_axes(self):
        """Axes whose gradient psum is per-leaf spec-gated: leaves SHARDED
        over the axis carry exact shard gradients (no psum); replicated
        leaves' partials are summed.  tp/pp = redundant compute; ep = a
        data axis whose expert slabs already aggregated every rank's
        tokens through the all_to_all transpose."""
        return (self.tp_axis, self.pp_axis, self.ep_axis)

    def moe_cfg(self):
        """The models.moe config for this model's MoE MLP (single source
        of truth: init/specs/forward all derive from moe.py through it)."""
        from . import moe as _moe
        return _moe.MoEConfig(
            d_model=self.d_model, d_ff=self.d_ff,
            n_experts=self.n_experts, capacity_factor=self.capacity_factor,
            ep_axis=self.ep_axis, dtype=self.dtype)


def tiny(vocab_size: int = 256, d_model: int = 64, n_layers: int = 2,
         n_heads: int = 4, n_kv_heads: int = 2, d_ff: int = 128,
         max_seq: int = 128, **kw) -> LlamaConfig:
    """Small config for tests / dryruns."""
    return LlamaConfig(vocab_size=vocab_size, d_model=d_model,
                       n_layers=n_layers, n_heads=n_heads,
                       n_kv_heads=n_kv_heads, d_ff=d_ff, max_seq=max_seq, **kw)


def llama3_8b() -> LlamaConfig:
    return LlamaConfig()  # defaults above are the 8B geometry


# ------------------------------------------------------------------- params
def init_params(cfg: LlamaConfig, key) -> Dict:
    """Initialize the full (unsharded) parameter pytree."""
    k = iter(jax.random.split(key, 4 + 7 * cfg.n_layers))
    D, H, K, Hd, F = (cfg.d_model, cfg.n_heads, cfg.n_kv_heads,
                      cfg.head_dim, cfg.d_ff)
    dt = cfg.dtype

    def dense(key, fan_in, shape):
        return (jax.random.normal(key, shape, jnp.float32)
                * (1.0 / np.sqrt(fan_in))).astype(dt)

    layers = []
    for _ in range(cfg.n_layers):
        layer = {
            "attn_norm": jnp.ones((D,), dt),
            "wq": dense(next(k), D, (D, H * Hd)),
            "wk": dense(next(k), D, (D, K * Hd)),
            "wv": dense(next(k), D, (D, K * Hd)),
            "wo": dense(next(k), H * Hd, (H * Hd, D)),
            "mlp_norm": jnp.ones((D,), dt),
        }
        if cfg.n_experts:
            from . import moe as _moe
            layer["moe"] = _moe.init_params(cfg.moe_cfg(), next(k))
        else:
            layer |= {
                "w1": dense(next(k), D, (D, F)),
                "w3": dense(next(k), D, (D, F)),
                "w2": dense(next(k), F, (F, D)),
            }
        layers.append(layer)
    if cfg.pp_axis:
        # Stacked layout [n_layers, ...]: shard_map slices axis 0 over the
        # pp axis in order, so stage i holds the contiguous layer slab
        # [i*L/pp, (i+1)*L/pp).  tree_map so nested subtrees (MoE) stack.
        layers = jax.tree_util.tree_map(lambda *ls: jnp.stack(ls), *layers)
    return {
        "embed": dense(next(k), D, (cfg.vocab_size, D)),
        "layers": layers,
        "final_norm": jnp.ones((D,), dt),
        "lm_head": dense(next(k), D, (D, cfg.vocab_size)),
    }


def param_specs(cfg: LlamaConfig) -> Dict:
    """PartitionSpec tree matching ``init_params`` (tp shards within a
    layer, pp shards the stacked layer axis; params are replicated over
    dp/sp)."""
    tp = cfg.tp_axis
    layer = {
        "attn_norm": P(),
        "wq": P(None, tp),
        "wk": P(None, tp),
        "wv": P(None, tp),
        "wo": P(tp, None),
        "mlp_norm": P(),
    }
    if cfg.n_experts:
        from . import moe as _moe
        layer["moe"] = _moe.param_specs(cfg.moe_cfg())
    else:
        layer |= {
            "w1": P(None, tp),
            "w3": P(None, tp),
            "w2": P(tp, None),
        }
    if cfg.pp_axis:
        layers = jax.tree_util.tree_map(
            lambda spec: P(cfg.pp_axis, *spec), layer,
            is_leaf=lambda x: isinstance(x, P))
    else:
        layers = [jax.tree_util.tree_map(
            lambda s: s, layer, is_leaf=lambda x: isinstance(x, P))
            for _ in range(cfg.n_layers)]
    return {
        "embed": P(),
        "layers": layers,
        "final_norm": P(),
        "lm_head": P(),
    }


# ------------------------------------------------------------------ forward
def _rmsnorm(x, w, eps=1e-5):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x.astype(jnp.float32) * lax.rsqrt(var + eps)).astype(x.dtype) * w


def _rope(x, positions, theta):
    """Rotary embeddings; x: [B, T, H, Hd], positions: [T]."""
    B, T, H, Hd = x.shape
    half = Hd // 2
    freqs = 1.0 / (theta ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    angles = positions[:, None].astype(jnp.float32) * freqs[None, :]  # [T, half]
    cos = jnp.cos(angles)[None, :, None, :]
    sin = jnp.sin(angles)[None, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    xf1, xf2 = x1.astype(jnp.float32), x2.astype(jnp.float32)
    return jnp.concatenate(
        [xf1 * cos - xf2 * sin, xf2 * cos + xf1 * sin], axis=-1).astype(x.dtype)


def _use_pallas_flash(cfg: "LlamaConfig") -> bool:
    """Pallas flash attention on TPU by default (the [Tq,Tk] scores never
    touch HBM — ops/flash_attention.py).  ``cfg.use_flash`` decides when
    set; otherwise HVD_TPU_FLASH=1/0 forces it on (interpret mode off-TPU,
    for tests) or off — read at TRACE time only (see LlamaConfig)."""
    from ..ops.flash_attention import resolve_flash
    return resolve_flash(cfg.use_flash)


def _attention(x, p, cfg: LlamaConfig, positions):
    """Self-attention on the local tp shard of heads; sp-ring over sequence."""
    B, T, D = x.shape
    tp = lax.axis_size(cfg.tp_axis) if cfg.tp_axis else 1
    if cfg.n_heads % tp or cfg.n_kv_heads % tp:
        raise ValueError(f"n_heads={cfg.n_heads}/n_kv_heads={cfg.n_kv_heads} "
                         f"must be divisible by tp={tp}")
    H_loc = cfg.n_heads // tp
    K_loc = cfg.n_kv_heads // tp
    Hd = cfg.head_dim

    q = (x @ p["wq"]).reshape(B, T, H_loc, Hd)
    kk = (x @ p["wk"]).reshape(B, T, K_loc, Hd)
    v = (x @ p["wv"]).reshape(B, T, K_loc, Hd)
    q = _rope(q, positions, cfg.rope_theta)
    kk = _rope(kk, positions, cfg.rope_theta)

    sp = lax.axis_size(cfg.sp_axis) if cfg.sp_axis else 1
    if sp > 1 and cfg.sp_impl == "ulysses":
        # Head exchange instead of kv rotation (docs/parallelism.md for
        # the tradeoff); GQA kv travels un-repeated through the alltoall.
        from ..ops.flash_attention import flash_attention
        from ..parallel.ulysses import ulysses_attention
        attn = (flash_attention if _use_pallas_flash(cfg)
                else local_flash_attention)   # same routing as every path
        out = ulysses_attention(q, kk, v, attn_fn=attn,
                                axis_name=cfg.sp_axis, causal=True)
    elif sp > 1:
        # GQA passes through un-repeated: the ring handles it on both
        # engines (pallas reads shared kv heads through block index maps —
        # H/K× less ring traffic; the jnp fallback repeats internally).
        out = ring_attention(q, kk, v, axis_name=cfg.sp_axis, causal=True,
                             use_flash=cfg.use_flash)
    elif _use_pallas_flash(cfg):
        from ..ops.flash_attention import flash_attention
        out = flash_attention(q, kk, v, causal=True)
    else:
        out = local_flash_attention(q, kk, v, causal=True)
    out = out.reshape(B, T, H_loc * Hd) @ p["wo"]
    if cfg.tp_axis:
        out = lax.psum(out, cfg.tp_axis)      # row-parallel output proj
    return out


def _mlp(x, p, cfg: LlamaConfig):
    """Dense SwiGLU MLP, or Switch-routed MoE when cfg.n_experts > 0.

    MoE returns ``(y, aux)``; dense returns ``(y, 0.0)`` so call sites are
    uniform.  The MoE path is NOT tp-split (experts shard over ep; every
    tp rank computes the same routing/experts redundantly — acceptable at
    the tp degrees attention wants, and it keeps the exchange one
    all_to_all instead of a tp×ep lattice)."""
    if cfg.n_experts:
        from . import moe as _moe
        B, T, D = x.shape
        y, aux = _moe.moe_ffn(x.reshape(B * T, D), p["moe"], cfg.moe_cfg())
        return y.reshape(B, T, D), aux
    h = jax.nn.silu(x @ p["w1"]) * (x @ p["w3"])
    out = h @ p["w2"]
    if cfg.tp_axis:
        out = lax.psum(out, cfg.tp_axis)
    return out, jnp.zeros((), jnp.float32)


def _layer_apply(p, x, cfg: LlamaConfig, positions):
    x = x + _attention(_rmsnorm(x, p["attn_norm"]), p, cfg, positions)
    y, aux = _mlp(_rmsnorm(x, p["mlp_norm"]), p, cfg)
    return x + y, aux


def forward(params, tokens, cfg: LlamaConfig):
    """Logits for local token shard (public surface; see _forward)."""
    return _forward(params, tokens, cfg)[0]


def _forward(params, tokens, cfg: LlamaConfig):
    """(logits, aux) for local token shard [B_loc, T_loc] (call inside
    shard_map, or directly when all axes are disabled/size-1).  ``aux`` is
    the summed MoE load-balance loss (0 for dense models).

    With ``pp_axis`` set, ``params["layers"]`` is this stage's slab of the
    stacked layer arrays and the blocks run under the GPipe microbatch
    schedule; embedding and the LM head are computed replicated on every
    stage (cheap next to the blocks), with the head reading the last
    stage's pipeline output broadcast via the zero-sum psum trick."""
    B, T = tokens.shape
    if cfg.sp_axis:
        sp_idx = lax.axis_index(cfg.sp_axis)
        positions = sp_idx * T + jnp.arange(T)
    else:
        positions = jnp.arange(T)
    x = params["embed"][tokens]
    aux_total = jnp.zeros((), jnp.float32)
    if cfg.pp_axis:
        from ..parallel.pipeline import microbatch, pipeline_apply
        M = cfg.n_microbatches
        micro_x = microbatch(x, M)           # [M, B/M, T, D]

        def stage_fn(slab, xm):
            def body(carry, p):
                h, aux = carry
                h, a = _layer_apply(p, h, cfg, positions)
                return (h, aux + a), None
            (h, aux), _ = lax.scan(
                body, (xm, jnp.zeros((), jnp.float32)), slab)
            return h, aux

        x, aux_total = pipeline_apply(
            stage_fn, params["layers"], micro_x, axis_name=cfg.pp_axis,
            broadcast_out=True, remat=cfg.remat_stages, with_aux=True)
        # moe aux is a per-token MEAN (batch-size invariant); the pipeline
        # accumulated one per microbatch, so average — otherwise the
        # scheduling knob n_microbatches would scale the training
        # objective.
        aux_total = aux_total / M
        x = x.reshape((B, T, -1))
    else:
        for p in params["layers"]:
            x, aux = _layer_apply(p, x, cfg, positions)
            aux_total = aux_total + aux
    x = _rmsnorm(x, params["final_norm"])
    return x @ params["lm_head"], aux_total


def loss_fn(params, tokens, targets, cfg: LlamaConfig):
    """PARTIAL next-token cross-entropy: this rank's contribution to the
    global mean.

    Written for shard_map's sum-semantics autodiff (the transpose of an
    in-graph psum is psum): the differentiated function contains NO loss
    psum; instead per-rank partial losses are scaled so they sum to the true
    global mean across every mesh axis — 1/(global_count) for the dp/sp data
    split and 1/tp for the redundant tensor-parallel compute.  ``sync_grads``
    then turns per-rank partial grads into the exact mean gradient, and
    ``psum_loss`` recovers the scalar for logging.
    """
    logits, aux = _forward(params, tokens, cfg)
    logits = logits.astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    # dp/sp/ep factors extend the local count to the global token count
    # (ep is a data axis when MoE is on); the tp/pp factors split the
    # redundantly-computed loss across ranks (every tp rank computes the
    # full head; every pp stage computes the loss from the broadcast
    # pipeline output).
    denom = float(nll.size)
    axes_denom = 1.0
    for ax in cfg.all_axes:
        if ax:
            axes_denom = axes_denom * lax.axis_size(ax)
    total = jnp.sum(nll) / (denom * axes_denom)
    if cfg.n_experts:
        # Per-rank mean router-balance loss (mean over layers), scaled so
        # the psum over every axis yields the cross-rank mean.  Unlike the
        # nll (redundant over pp via the broadcast output), aux is
        # PARTITIONED over pp — each stage computed only its own slab's
        # routers — so pp's factor must not divide it.
        aux_denom = axes_denom
        if cfg.pp_axis:
            aux_denom = aux_denom / lax.axis_size(cfg.pp_axis)
        total = total + (cfg.aux_weight * aux / cfg.n_layers) / aux_denom
    return total


def psum_loss(loss_partial, cfg: LlamaConfig):
    """Sum per-rank partial losses into the true global mean loss."""
    for ax in cfg.all_axes:
        if ax:
            loss_partial = lax.psum(loss_partial, ax)
    return loss_partial


# --------------------------------------------------------------- train step
def sync_grads(grads, cfg: LlamaConfig, specs=None):
    """Cross-rank gradient synchronization for the explicit-SPMD step.

    Under sum-semantics autodiff each rank's grad is its partial
    contribution, so:

    - ALL params: psum over dp (the Horovod allreduce) and sp (each sp rank
      saw a different sequence chunk).
    - tp-replicated params only (norms, embed, lm_head): additionally psum
      over tp to combine the per-shard contributions; tp-SHARDED params'
      grads are already exact for their shard (the cotangent arriving
      through the row-parallel psum's transpose is the full one).
    - pp-replicated params (embed/lm_head/final_norm): psum over pp — the
      embed grad is nonzero only on stage 0 (the pipeline consumes input
      there) and the head grad is 1/pp-scaled on every stage, so the psum
      reassembles both.  pp-SHARDED slabs are exact per stage, like tp.
    - ep (MoE): a data axis — non-expert leaves saw only this rank's
      token shard (psum over ep like dp/sp), while ep-SHARDED expert
      slabs already aggregated every ep rank's tokens through the
      all_to_all transpose (exact, no psum).
    The 1/(count·tp·pp·ep) scaling inside ``loss_fn`` makes these psums
    land on the exact global-mean gradient.
    """
    specs = specs or param_specs(cfg)
    gated = cfg.spec_gated_axes

    def leaf_sync(g, spec):
        for ax in (cfg.dp_axis, cfg.sp_axis):
            if ax:
                g = lax.psum(g, ax)
        for ax in gated:
            if ax and all(s != ax for s in spec):
                g = lax.psum(g, ax)
        return g

    return jax.tree_util.tree_map(leaf_sync, grads, specs,
                                  is_leaf=lambda x: isinstance(x, P))


# ---------------------------------------------------------------- inference
def init_cache(cfg: LlamaConfig, batch: int, max_seq: Optional[int] = None):
    """Per-layer KV cache ``[B, max_seq, n_kv_heads, head_dim]`` (zeros).

    Beyond-reference: Horovod ships no inference path at all; this is the
    decode half of the flagship model.  Static shape — the cache is a
    fixed ring of ``max_seq`` slots written via dynamic_update_slice, so
    one compiled decode step serves every position.
    """
    T = max_seq or cfg.max_seq
    shape = (batch, T, cfg.n_kv_heads, cfg.head_dim)
    return [{"k": jnp.zeros(shape, cfg.dtype),
             "v": jnp.zeros(shape, cfg.dtype)}
            for _ in range(cfg.n_layers)]


def _check_cache_budget(t_final: int, cache_t: int):
    """Every position is static at trace time — refuse to decode past the
    cache instead of letting dynamic_update_slice clamp writes onto the
    last slot (which silently corrupts every later token)."""
    if t_final > cache_t:
        raise ValueError(
            f"decode would write position {t_final - 1} but the KV cache "
            f"has only {cache_t} slots; raise max_seq (init_cache) or "
            f"generate fewer tokens")


def decode_step(params, cache, tokens, pos, cfg: LlamaConfig):
    """One greedy-decode step: ``tokens [B]`` at position ``pos`` (traced
    scalar) -> (logits [B, vocab], updated cache).

    Single-device decode (axes must be disabled — decode batching is the
    deployment-level concern; training parallelism stays in the train
    path).  Attention over the cache is a plain masked einsum: at Tq=1
    there is no score matrix to tile, so flash buys nothing.
    """
    if any(ax for ax in cfg.all_axes):
        raise ValueError("decode_step expects a config with all mesh axes "
                         "disabled (dp/tp/sp/pp/ep = None)")
    B = tokens.shape[0]
    H, K, Hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    x = params["embed"][tokens][:, None, :]          # [B, 1, D]
    positions = jnp.full((1,), pos, jnp.int32)
    new_cache = []
    T = cache[0]["k"].shape[1]
    valid = (jnp.arange(T) <= pos)[None, None, None, :]   # [1,1,1,T]
    for p, c in zip(params["layers"], cache):
        h = _rmsnorm(x, p["attn_norm"])
        q = (h @ p["wq"]).reshape(B, 1, H, Hd)
        k_new = (h @ p["wk"]).reshape(B, 1, K, Hd)
        v_new = (h @ p["wv"]).reshape(B, 1, K, Hd)
        q = _rope(q, positions, cfg.rope_theta)
        k_new = _rope(k_new, positions, cfg.rope_theta)
        ck = lax.dynamic_update_slice(c["k"], k_new.astype(c["k"].dtype),
                                      (0, pos, 0, 0))
        cv = lax.dynamic_update_slice(c["v"], v_new.astype(c["v"].dtype),
                                      (0, pos, 0, 0))
        new_cache.append({"k": ck, "v": cv})
        # GQA: fold q heads into [K, rep] groups against the shared kv.
        qg = q.reshape(B, K, H // K, Hd)             # Tq=1 squeezed
        s = jnp.einsum("bkrd,btkd->bkrt", qg, ck,
                       preferred_element_type=jnp.float32)
        s = s / np.sqrt(Hd)
        s = jnp.where(valid, s, NEG_INF)
        w = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bkrt,btkd->bkrd", w.astype(cv.dtype), cv,
                       preferred_element_type=jnp.float32)
        o = o.reshape(B, 1, H * Hd).astype(x.dtype) @ p["wo"]
        x = x + o
        y, _ = _mlp(_rmsnorm(x, p["mlp_norm"]), p, cfg)
        x = x + y
    x = _rmsnorm(x, params["final_norm"])
    return (x[:, 0, :] @ params["lm_head"]).astype(jnp.float32), new_cache


def prefill(params, cache, tokens, cfg: LlamaConfig):
    """Fill the cache from a prompt ``[B, T0]`` by scanning decode_step;
    returns (last logits, cache).  O(T0·T) — fine for the test/bench
    vehicle; a blockwise flash prefill is the production variant."""
    B, T0 = tokens.shape
    _check_cache_budget(T0, cache[0]["k"].shape[1])

    def body(carry, t):
        cache = carry
        logits, cache = decode_step(params, cache, tokens[:, t], t, cfg)
        return cache, logits

    cache, logits = lax.scan(body, cache, jnp.arange(T0))
    return logits[-1], cache


def generate(params, prompt, n_tokens: int, cfg: LlamaConfig,
             max_seq: Optional[int] = None):
    """Greedy generation: ``prompt [B, T0]`` -> ``[B, n_tokens]``.

    jit-compatible end to end (scan over a static token budget)."""
    B, T0 = prompt.shape
    if n_tokens < 1:
        return jnp.zeros((B, 0), jnp.int32)
    cache = init_cache(cfg, B, max_seq)
    # The last generated token's own kv is never written back, hence -1.
    _check_cache_budget(T0 + n_tokens - 1, cache[0]["k"].shape[1])
    logits, cache = prefill(params, cache, prompt, cfg)

    def body(carry, t):
        tok, cache = carry
        logits, cache = decode_step(params, cache, tok, t, cfg)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return (nxt, cache), nxt

    first = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    (_, _), rest = lax.scan(body, (first, cache),
                            jnp.arange(T0, T0 + n_tokens - 1))
    return jnp.concatenate([first[:, None], rest.T], axis=1)


def make_train_step(cfg: LlamaConfig, optimizer):
    """Returns ``step(params, opt_state, tokens, targets) -> (params,
    opt_state, loss)`` for use inside shard_map over (dp, sp, tp)."""
    import optax

    def step(params, opt_state, tokens, targets):
        loss_partial, grads = jax.value_and_grad(loss_fn)(
            params, tokens, targets, cfg)
        grads = sync_grads(grads, cfg)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, psum_loss(loss_partial, cfg)

    return step
