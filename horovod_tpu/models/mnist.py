"""MNIST CNN — BASELINE config #1 (reference:
``examples/pytorch/pytorch_mnist.py``).

The canonical end-to-end smoke: a small convnet trained data-parallel with
``hvd.DistributedOptimizer`` + ``broadcast_parameters``, here as an explicit
shard_map step over the ``hvd``/``dp`` axis.  Runs on synthetic digits when
the real dataset isn't on disk (this image has no network).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax

from ..compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P
from ..compat import axis_size as compat_axis_size


def init_params(key, dtype=jnp.float32):
    k1, k2, k3, k4 = jax.random.split(key, 4)

    def he(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32)
                * np.sqrt(2.0 / fan_in)).astype(dtype)

    return {
        "conv1": {"w": he(k1, (3, 3, 1, 32), 9), "b": jnp.zeros((32,), dtype)},
        "conv2": {"w": he(k2, (3, 3, 32, 64), 9 * 32),
                  "b": jnp.zeros((64,), dtype)},
        "fc1": {"w": he(k3, (7 * 7 * 64, 128), 7 * 7 * 64),
                "b": jnp.zeros((128,), dtype)},
        "fc2": {"w": he(k4, (128, 10), 128), "b": jnp.zeros((10,), dtype)},
    }


def forward(params, x):
    """x: [B, 28, 28, 1] -> logits [B, 10]."""
    def conv(x, p):
        y = lax.conv_general_dilated(
            x, p["w"], window_strides=(1, 1), padding="SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        return y + p["b"]

    x = jax.nn.relu(conv(x, params["conv1"]))
    x = lax.reduce_window(x, -jnp.inf, lax.max, (1, 2, 2, 1), (1, 2, 2, 1),
                          "VALID")
    x = jax.nn.relu(conv(x, params["conv2"]))
    x = lax.reduce_window(x, -jnp.inf, lax.max, (1, 2, 2, 1), (1, 2, 2, 1),
                          "VALID")
    x = x.reshape(x.shape[0], -1)
    x = jax.nn.relu(x @ params["fc1"]["w"] + params["fc1"]["b"])
    return x @ params["fc2"]["w"] + params["fc2"]["b"]


def loss_fn(params, x, y, axis_name: Optional[str] = "hvd"):
    """Partial mean NLL (sum-semantics; see models/llama.py loss_fn)."""
    logits = forward(params, x).astype(jnp.float32)
    logp = jax.nn.log_softmax(logits)
    nll = -jnp.take_along_axis(logp, y[:, None], axis=-1)[:, 0]
    denom = float(nll.size)
    if axis_name:
        denom = denom * compat_axis_size(axis_name)
    return jnp.sum(nll) / denom


def make_train_step(optimizer, axis_name: Optional[str] = "hvd",
                    reduce_grads: bool = True):
    """Per-shard DP train step: grads psum'd over the world axis — the
    DistributedOptimizer pattern of SURVEY.md §3.2 in explicit SPMD.

    ``reduce_grads=False`` hands RAW per-shard gradients to the optimizer
    — for optimizers that own their reduction, like the ZeRO
    ``parallel.zero.sharded_optimizer`` whose update reduce-scatters (a
    pre-psum would double-reduce)."""

    def step(params, opt_state, x, y):
        loss_partial, grads = jax.value_and_grad(loss_fn)(params, x, y,
                                                          axis_name)
        if axis_name and reduce_grads:
            grads = jax.tree_util.tree_map(
                lambda g: lax.psum(g, axis_name), grads)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        loss = lax.psum(loss_partial, axis_name) if axis_name else loss_partial
        return params, opt_state, loss

    return step


def make_sharded_train_step(optimizer, mesh: Mesh, axis_name: str = "hvd",
                            zero_specs=None):
    """Compiled shard_map train step.

    ``zero_specs`` (ISSUE 15): pass the opt-state spec tree from
    ``parallel.zero.init_sharded_state(optimizer, params, mesh,
    axis_name)`` to train with a ZeRO-sharded optimizer — the step then
    wraps ``optimizer`` in ``parallel.zero.sharded_optimizer`` (raw
    grads in, reduce-scatter inside, 1/world optimizer state per device)
    and shards the opt state accordingly.  ``None`` keeps the legacy
    replicated-state path.
    """
    if zero_specs is None:
        step = make_train_step(optimizer, axis_name)
        opt_specs = P()
    else:
        from ..parallel.zero import sharded_optimizer
        # average=False: the replicated path psums (the loss already
        # carries the 1/world factor), so the scatter must SUM too.
        step = make_train_step(
            sharded_optimizer(optimizer, axis_name, average=False),
            axis_name, reduce_grads=False)
        opt_specs = zero_specs
    return jax.jit(shard_map(
        step, mesh=mesh,
        in_specs=(P(), opt_specs, P(axis_name), P(axis_name)),
        out_specs=(P(), opt_specs, P()), check_vma=False),
        donate_argnums=(0, 1))


def synthetic_batch(batch: int, seed: int = 0) -> Tuple[np.ndarray, np.ndarray]:
    """Deterministic fake digits: class-dependent blobs + noise."""
    rng = np.random.RandomState(seed)
    y = rng.randint(0, 10, size=(batch,)).astype(np.int32)
    x = rng.randn(batch, 28, 28, 1).astype(np.float32) * 0.1
    for i, cls in enumerate(y):
        r, c = divmod(int(cls), 4)
        x[i, 4 + r * 6:10 + r * 6, 4 + c * 6:10 + c * 6, 0] += 1.0
    return x, y
