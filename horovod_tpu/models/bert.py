"""BERT encoder — BASELINE config #3 ("BERT-Large pretraining with
DistributedOptimizer + fp16/bf16 fused allreduce").

Explicit-SPMD like ``models/llama.py`` (shared conventions: Megatron tp for
attention/FFN, optional sp via Ulysses head-exchange — bidirectional
attention makes Ulysses the natural sp scheme rather than a causal ring —
sum-semantics partial loss, spec-aware grad sync).  LayerNorm + GELU + learned
positions per the BERT architecture; MLM loss over masked positions.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P
from ..compat import axis_size as compat_axis_size

from ..parallel.ring_attention import local_flash_attention
from ..parallel.ulysses import ulysses_attention


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    d_model: int = 1024          # BERT-Large
    n_layers: int = 24
    n_heads: int = 16
    d_ff: int = 4096
    max_seq: int = 512
    dtype: Any = jnp.bfloat16
    dp_axis: Optional[str] = "dp"
    tp_axis: Optional[str] = "tp"
    sp_axis: Optional[str] = "sp"
    # Pallas flash attention: True/False, or None = HVD_TPU_FLASH / auto at
    # TRACE time (same semantics as LlamaConfig.use_flash).
    use_flash: Optional[bool] = None

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


def bert_large() -> BertConfig:
    return BertConfig()


def tiny(**kw) -> BertConfig:
    defaults = dict(vocab_size=256, d_model=64, n_layers=2, n_heads=4,
                    d_ff=128, max_seq=64)
    defaults.update(kw)
    return BertConfig(**defaults)


def init_params(cfg: BertConfig, key) -> Dict:
    k = iter(jax.random.split(key, 8 + 6 * cfg.n_layers))
    D, H, Hd, F = cfg.d_model, cfg.n_heads, cfg.head_dim, cfg.d_ff
    dt = cfg.dtype

    def dense(key, fan_in, shape):
        return (jax.random.normal(key, shape, jnp.float32)
                * (1.0 / np.sqrt(fan_in))).astype(dt)

    layers = []
    for _ in range(cfg.n_layers):
        layers.append({
            "ln1_scale": jnp.ones((D,), dt), "ln1_bias": jnp.zeros((D,), dt),
            "wq": dense(next(k), D, (D, H * Hd)),
            "wk": dense(next(k), D, (D, H * Hd)),
            "wv": dense(next(k), D, (D, H * Hd)),
            "wo": dense(next(k), H * Hd, (H * Hd, D)),
            "ln2_scale": jnp.ones((D,), dt), "ln2_bias": jnp.zeros((D,), dt),
            "w_in": dense(next(k), D, (D, F)),
            "b_in": jnp.zeros((F,), dt),
            "w_out": dense(next(k), F, (F, D)),
            "b_out": jnp.zeros((D,), dt),
        })
    return {
        "tok_embed": dense(next(k), D, (cfg.vocab_size, D)),
        "pos_embed": dense(next(k), D, (cfg.max_seq, D)),
        "layers": layers,
        "final_ln_scale": jnp.ones((D,), dt),
        "final_ln_bias": jnp.zeros((D,), dt),
        "mlm_head": dense(next(k), D, (D, cfg.vocab_size)),
    }


def param_specs(cfg: BertConfig) -> Dict:
    tp = cfg.tp_axis
    layer = {
        "ln1_scale": P(), "ln1_bias": P(),
        "wq": P(None, tp), "wk": P(None, tp), "wv": P(None, tp),
        "wo": P(tp, None),
        "ln2_scale": P(), "ln2_bias": P(),
        "w_in": P(None, tp), "b_in": P(tp),
        "w_out": P(tp, None), "b_out": P(),
    }
    return {
        "tok_embed": P(), "pos_embed": P(),
        "layers": [dict(layer) for _ in range(cfg.n_layers)],
        "final_ln_scale": P(), "final_ln_bias": P(),
        "mlm_head": P(),
    }


def _layernorm(x, scale, bias, eps=1e-5):
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    return ((xf - mu) * lax.rsqrt(var + eps)).astype(x.dtype) * scale + bias


def _attention(x, p, cfg: BertConfig):
    B, T, D = x.shape
    tp = compat_axis_size(cfg.tp_axis) if cfg.tp_axis else 1
    if cfg.n_heads % tp:
        raise ValueError(f"n_heads={cfg.n_heads} not divisible by tp={tp}")
    H_loc, Hd = cfg.n_heads // tp, cfg.head_dim
    q = (x @ p["wq"]).reshape(B, T, H_loc, Hd)
    k = (x @ p["wk"]).reshape(B, T, H_loc, Hd)
    v = (x @ p["wv"]).reshape(B, T, H_loc, Hd)
    sp = compat_axis_size(cfg.sp_axis) if cfg.sp_axis else 1
    if sp > 1:
        # ulysses_attention itself routes to the pallas kernel on TPU.
        out = ulysses_attention(q, k, v, axis_name=cfg.sp_axis, causal=False)
    else:
        from ..ops.flash_attention import flash_attention, resolve_flash
        if resolve_flash(cfg.use_flash, seq=T):
            out = flash_attention(q, k, v, causal=False)
        else:
            out = local_flash_attention(q, k, v, causal=False)
    out = out.reshape(B, T, H_loc * Hd) @ p["wo"]
    if cfg.tp_axis:
        out = lax.psum(out, cfg.tp_axis)
    return out


def _ffn(x, p, cfg: BertConfig):
    h = jax.nn.gelu(x @ p["w_in"] + p["b_in"])
    out = h @ p["w_out"]
    if cfg.tp_axis:
        out = lax.psum(out, cfg.tp_axis)
    return out + p["b_out"]


def forward(params, tokens, cfg: BertConfig):
    """Encoder states for the local token shard [B_loc, T_loc]."""
    B, T = tokens.shape
    if cfg.sp_axis:
        sp_idx = lax.axis_index(cfg.sp_axis)
        positions = sp_idx * T + jnp.arange(T)
    else:
        positions = jnp.arange(T)
    x = params["tok_embed"][tokens] + params["pos_embed"][positions][None]
    for p in params["layers"]:
        x = x + _attention(_layernorm(x, p["ln1_scale"], p["ln1_bias"]),
                           p, cfg)
        x = x + _ffn(_layernorm(x, p["ln2_scale"], p["ln2_bias"]), p, cfg)
    x = _layernorm(x, params["final_ln_scale"], params["final_ln_bias"])
    return x


def mlm_loss_fn(params, tokens, targets, mask, cfg: BertConfig):
    """Partial masked-LM loss (sum-semantics; see llama.loss_fn).

    ``mask`` is 1.0 at masked positions.  The denominator is the GLOBAL mask
    count — psum'd over dp/sp, which is safe under sum-semantics autodiff
    because no parameter cotangent flows through the mask — times tp for the
    redundant tensor-parallel compute.
    """
    x = forward(params, tokens, cfg)
    logits = (x @ params["mlm_head"]).astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    local_sum = jnp.sum(nll * mask)
    denom = jnp.sum(mask)
    for ax in (cfg.dp_axis, cfg.sp_axis):
        if ax:
            denom = lax.psum(denom, ax)
    denom = jnp.maximum(denom, 1.0)
    if cfg.tp_axis:
        denom = denom * compat_axis_size(cfg.tp_axis)
    return local_sum / denom


def psum_loss(loss_partial, cfg: BertConfig):
    for ax in (cfg.dp_axis, cfg.sp_axis, cfg.tp_axis):
        if ax:
            loss_partial = lax.psum(loss_partial, ax)
    return loss_partial


def sync_grads(grads, cfg: BertConfig, specs=None):
    specs = specs or param_specs(cfg)

    def leaf_sync(g, spec):
        for ax in (cfg.dp_axis, cfg.sp_axis):
            if ax:
                g = lax.psum(g, ax)
        if cfg.tp_axis and all(s != cfg.tp_axis for s in spec):
            g = lax.psum(g, cfg.tp_axis)
        return g

    return jax.tree_util.tree_map(leaf_sync, grads, specs,
                                  is_leaf=lambda x: isinstance(x, P))


def make_train_step(cfg: BertConfig, optimizer):
    import optax

    def step(params, opt_state, tokens, targets, mask):
        loss_partial, grads = jax.value_and_grad(mlm_loss_fn)(
            params, tokens, targets, mask, cfg)
        grads = sync_grads(grads, cfg)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, psum_loss(loss_partial, cfg)

    return step
