"""ResNet-50 — BASELINE config #2, the canonical Horovod benchmark
(reference: ``examples/pytorch/pytorch_imagenet_resnet50.py`` and
``*_synthetic_benchmark.py``; published numbers in ``docs/benchmarks.rst``).

TPU-first notes: NHWC layout, bf16 compute / f32 batch-norm statistics and
params (the MXU-friendly mixed precision), cross-replica SyncBatchNorm via
psum over the dp axis (parity with the reference's
``horovod/torch/sync_batch_norm.py``), explicit-SPMD train step like the
other models.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax

from ..compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P
from ..compat import axis_size as compat_axis_size

BLOCKS = {18: (2, 2, 2, 2), 34: (3, 4, 6, 3), 50: (3, 4, 6, 3),
          101: (3, 4, 23, 3), 152: (3, 8, 36, 3)}
BOTTLENECK = {50, 101, 152}


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    depth: int = 50
    num_classes: int = 1000
    width: int = 64
    compute_dtype: Any = jnp.bfloat16
    bn_momentum: float = 0.9
    bn_eps: float = 1e-5
    sync_bn_axis: Optional[str] = "hvd"   # cross-replica batch norm axis


def _conv_init(key, shape):
    fan_in = shape[0] * shape[1] * shape[2]
    return jax.random.normal(key, shape, jnp.float32) * np.sqrt(2.0 / fan_in)


def _bn_init(ch):
    return {"scale": jnp.ones((ch,), jnp.float32),
            "bias": jnp.zeros((ch,), jnp.float32)}


def _bn_stats(ch):
    return {"mean": jnp.zeros((ch,), jnp.float32),
            "var": jnp.ones((ch,), jnp.float32)}


def init_params(cfg: ResNetConfig, key):
    """Returns (params, batch_stats)."""
    keys = iter(jax.random.split(key, 1024))
    stages = BLOCKS[cfg.depth]
    bottleneck = cfg.depth in BOTTLENECK
    expansion = 4 if bottleneck else 1

    params: dict = {"stem": {"w": _conv_init(next(keys), (7, 7, 3, cfg.width)),
                             "bn": _bn_init(cfg.width)}}
    stats: dict = {"stem": _bn_stats(cfg.width)}
    in_ch = cfg.width
    for si, n_blocks in enumerate(stages):
        out_ch = cfg.width * (2 ** si) * expansion
        mid_ch = cfg.width * (2 ** si)
        blocks_p, blocks_s = [], []
        for bi in range(n_blocks):
            stride = 2 if (si > 0 and bi == 0) else 1
            bp: dict = {}
            bs: dict = {}
            if bottleneck:
                shapes = [(1, 1, in_ch, mid_ch), (3, 3, mid_ch, mid_ch),
                          (1, 1, mid_ch, out_ch)]
            else:
                shapes = [(3, 3, in_ch, mid_ch), (3, 3, mid_ch, out_ch)]
            for ci, shp in enumerate(shapes):
                bp[f"conv{ci}"] = {"w": _conv_init(next(keys), shp),
                                   "bn": _bn_init(shp[-1])}
                bs[f"conv{ci}"] = _bn_stats(shp[-1])
            if in_ch != out_ch or stride != 1:
                bp["proj"] = {"w": _conv_init(next(keys),
                                              (1, 1, in_ch, out_ch)),
                              "bn": _bn_init(out_ch)}
                bs["proj"] = _bn_stats(out_ch)
            blocks_p.append(bp)
            blocks_s.append(bs)
            in_ch = out_ch
        params[f"stage{si}"] = blocks_p
        stats[f"stage{si}"] = blocks_s
    params["fc"] = {"w": jax.random.normal(next(keys), (in_ch, cfg.num_classes),
                                           jnp.float32) * 0.01,
                    "b": jnp.zeros((cfg.num_classes,), jnp.float32)}
    return params, stats


def _batch_norm(x, bn, stats, cfg: ResNetConfig, train: bool):
    """BN in f32 with optional cross-replica (Sync) statistics.

    Parity: ``horovod/torch/sync_batch_norm.py`` — mean/var are averaged
    over the dp axis with psum before normalization.
    """
    xf = x.astype(jnp.float32)
    if train:
        axes = (0, 1, 2)
        mean = jnp.mean(xf, axis=axes)
        mean2 = jnp.mean(jnp.square(xf), axis=axes)
        if cfg.sync_bn_axis:
            n = compat_axis_size(cfg.sync_bn_axis)
            mean = lax.psum(mean, cfg.sync_bn_axis) / n
            mean2 = lax.psum(mean2, cfg.sync_bn_axis) / n
        var = mean2 - jnp.square(mean)
        new_stats = {
            "mean": cfg.bn_momentum * stats["mean"]
                    + (1 - cfg.bn_momentum) * mean,
            "var": cfg.bn_momentum * stats["var"]
                   + (1 - cfg.bn_momentum) * var,
        }
    else:
        mean, var = stats["mean"], stats["var"]
        new_stats = stats
    y = (xf - mean) * lax.rsqrt(var + cfg.bn_eps) * bn["scale"] + bn["bias"]
    return y.astype(x.dtype), new_stats


def _conv(x, w, stride=1):
    return lax.conv_general_dilated(
        x, w.astype(x.dtype), window_strides=(stride, stride), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def forward(params, stats, images, cfg: ResNetConfig, train: bool = True):
    """images [B, H, W, 3] -> (logits [B, classes], new_stats)."""
    x = images.astype(cfg.compute_dtype)
    new_stats: dict = {}

    y = _conv(x, params["stem"]["w"], stride=2)
    y, new_stats["stem"] = _batch_norm(y, params["stem"]["bn"], stats["stem"],
                                       cfg, train)
    y = jax.nn.relu(y)
    y = lax.reduce_window(y, -jnp.inf, lax.max, (1, 3, 3, 1), (1, 2, 2, 1),
                          "SAME")

    bottleneck = cfg.depth in BOTTLENECK
    for si in range(len(BLOCKS[cfg.depth])):
        blocks_p = params[f"stage{si}"]
        blocks_s = stats[f"stage{si}"]
        stage_stats = []
        for bi, (bp, bs) in enumerate(zip(blocks_p, blocks_s)):
            stride = 2 if (si > 0 and bi == 0) else 1
            res = y
            bstat: dict = {}
            n_convs = 3 if bottleneck else 2
            h = y
            for ci in range(n_convs):
                s = stride if ci == (1 if bottleneck else 0) else 1
                h = _conv(h, bp[f"conv{ci}"]["w"], stride=s)
                h, bstat[f"conv{ci}"] = _batch_norm(
                    h, bp[f"conv{ci}"]["bn"], bs[f"conv{ci}"], cfg, train)
                if ci < n_convs - 1:
                    h = jax.nn.relu(h)
            if "proj" in bp:
                res = _conv(res, bp["proj"]["w"], stride=stride)
                res, bstat["proj"] = _batch_norm(
                    res, bp["proj"]["bn"], bs["proj"], cfg, train)
            y = jax.nn.relu(h + res)
            stage_stats.append(bstat)
        new_stats[f"stage{si}"] = stage_stats

    y = jnp.mean(y.astype(jnp.float32), axis=(1, 2))
    logits = y @ params["fc"]["w"] + params["fc"]["b"]
    return logits, new_stats


def loss_fn(params, stats, images, labels, cfg: ResNetConfig,
            axis_name: Optional[str] = "hvd"):
    logits, new_stats = forward(params, stats, images, cfg, train=True)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    nll = -jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]
    denom = float(nll.size)
    if axis_name:
        denom = denom * compat_axis_size(axis_name)
    return jnp.sum(nll) / denom, new_stats


def make_train_step(cfg: ResNetConfig, optimizer,
                    axis_name: Optional[str] = "hvd"):
    def step(params, stats, opt_state, images, labels):
        (loss_partial, new_stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, stats, images, labels, cfg,
                                   axis_name)
        if axis_name:
            grads = jax.tree_util.tree_map(
                lambda g: lax.psum(g, axis_name), grads)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        loss = lax.psum(loss_partial, axis_name) if axis_name else loss_partial
        return params, new_stats, opt_state, loss

    return step


def make_sharded_train_step(cfg: ResNetConfig, optimizer, mesh: Mesh,
                            axis_name: str = "hvd"):
    step = make_train_step(cfg, optimizer, axis_name)
    return jax.jit(shard_map(
        step, mesh=mesh,
        in_specs=(P(), P(), P(), P(axis_name), P(axis_name)),
        out_specs=(P(), P(), P(), P()), check_vma=False),
        donate_argnums=(0, 1, 2))


def synthetic_batch(batch: int, image_size: int = 224,
                    num_classes: int = 1000,
                    seed: int = 0) -> Tuple[np.ndarray, np.ndarray]:
    rng = np.random.RandomState(seed)
    x = rng.randn(batch, image_size, image_size, 3).astype(np.float32)
    y = rng.randint(0, num_classes, size=(batch,)).astype(np.int32)
    return x, y
