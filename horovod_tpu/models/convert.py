"""Weight conversion: HuggingFace Llama/Mistral checkpoints -> llama.py.

Beyond the reference (Horovod ships no models, so no loaders either):
a user switching over brings their weights — this module maps the HF
``LlamaForCausalLM`` / ``MistralForCausalLM`` state-dict naming onto
``models/llama.py``'s parameter pytree, handling the two real layout
differences:

- **Linear orientation**: HF ``nn.Linear`` stores ``[out, in]``; this
  repo's matmuls are ``x @ W`` with ``W [in, out]`` — every projection
  transposes.
- **Rotary layout**: none needed — HF's ``rotate_half`` rope is the
  same half-split convention as ``_rope`` here (cos/sin over
  ``arange(0, d, 2)/d`` ≡ ``arange(d/2)/(d/2)``), so q/k convert by
  transpose alone.  (The per-head interleave "unpermute" from the
  original conversion scripts applies to META-format checkpoints, which
  HF's own converter already normalized — parity is pinned against
  ``transformers`` logits in ``tests/test_convert.py``.)

Input: any mapping of ``str -> array`` (a ``safetensors`` file opened
with ``numpy``, a ``torch.load`` state dict, or a dict of numpy arrays —
tensors are converted via ``np.asarray``; torch tensors are accepted
without importing torch).  Output: the exact pytree ``init_params``
produces, ready for ``shard_params``/``cache_specs``/decode.
"""

from __future__ import annotations

from typing import Any, Dict, Mapping

import jax.numpy as jnp
import numpy as np

from .llama import LlamaConfig


def _np(x) -> np.ndarray:
    """Accept numpy / jax / torch tensors without importing torch.
    Real checkpoints ship bf16, which numpy cannot represent — upcast to
    float32 (exact: every bf16 value is a float32)."""
    if hasattr(x, "detach"):          # torch.Tensor
        x = x.detach().cpu()
        if str(x.dtype) == "torch.bfloat16":
            x = x.float()
        x = x.numpy()
    return np.asarray(x)


def from_hf_state_dict(sd: Mapping[str, Any], cfg: LlamaConfig) -> Dict:
    """Map an HF Llama/Mistral state dict onto ``init_params``'s pytree.

    Expects the standard names (``model.layers.N.self_attn.q_proj.weight``
    etc.); raises KeyError naming the first missing tensor and ValueError
    on UNCONSUMED tensors (a 32-layer checkpoint against n_layers=16, or
    attention biases this architecture doesn't have, must not convert
    silently into a wrong model).  Output dtypes follow ``cfg.dtype``;
    norms stay as stored.  Match ``cfg.norm_eps`` to the checkpoint's
    ``rms_norm_eps``.
    """
    if cfg.n_experts and not (cfg.moe_gated and cfg.router_top_k >= 2):
        raise ValueError(
            "MoE conversion expects the Mixtral shape: moe_gated=True "
            "(SwiGLU experts) with router_top_k >= 2 (normalized top-k "
            "gates — top-1 Switch routing over top-2-trained weights "
            "would be silently wrong) — see mixtral_8x7b()")
    dt = cfg.dtype
    consumed = set()

    def get(name):
        if name not in sd:
            raise KeyError(
                f"state dict is missing {name!r} — is this a "
                f"LlamaForCausalLM/MistralForCausalLM checkpoint with "
                f"n_layers={cfg.n_layers}?")
        consumed.add(name)
        return _np(sd[name])

    def linear(name):
        return get(name).T          # HF [out, in] -> x @ W [in, out]

    layers = []
    for i in range(cfg.n_layers):
        pre = f"model.layers.{i}."
        layer = {
            "attn_norm": jnp.asarray(
                get(pre + "input_layernorm.weight"), dt),
            "wq": jnp.asarray(linear(pre + "self_attn.q_proj.weight"), dt),
            "wk": jnp.asarray(linear(pre + "self_attn.k_proj.weight"), dt),
            "wv": jnp.asarray(linear(pre + "self_attn.v_proj.weight"), dt),
            "wo": jnp.asarray(linear(pre + "self_attn.o_proj.weight"), dt),
            "mlp_norm": jnp.asarray(
                get(pre + "post_attention_layernorm.weight"), dt),
        }
        if cfg.n_experts:
            # MixtralForCausalLM sparse block: per-expert SwiGLU
            # (w1 gate, w3 up, w2 down — each nn.Linear [out, in]) plus
            # the router gate.  Stacked onto this repo's [E, ...] slabs.
            moe_pre = pre + "block_sparse_moe."
            layer["moe"] = {
                "router": jnp.asarray(linear(moe_pre + "gate.weight"), dt),
                "w1": jnp.asarray(np.stack(
                    [linear(f"{moe_pre}experts.{e}.w1.weight")
                     for e in range(cfg.n_experts)]), dt),
                "w3": jnp.asarray(np.stack(
                    [linear(f"{moe_pre}experts.{e}.w3.weight")
                     for e in range(cfg.n_experts)]), dt),
                "w2": jnp.asarray(np.stack(
                    [linear(f"{moe_pre}experts.{e}.w2.weight")
                     for e in range(cfg.n_experts)]), dt),
            }
        else:
            layer |= {
                "w1": jnp.asarray(linear(pre + "mlp.gate_proj.weight"),
                                  dt),
                "w3": jnp.asarray(linear(pre + "mlp.up_proj.weight"), dt),
                "w2": jnp.asarray(linear(pre + "mlp.down_proj.weight"),
                                  dt),
            }
        layers.append(layer)
    if cfg.pp_axis:
        import jax
        layers = jax.tree_util.tree_map(lambda *ls: jnp.stack(ls), *layers)

    embed = jnp.asarray(get("model.embed_tokens.weight"), dt)
    if "lm_head.weight" in sd:
        head = jnp.asarray(linear("lm_head.weight"), dt)
    else:
        # Tied embeddings (tie_word_embeddings=True).
        head = embed.T.astype(dt)
    norm = jnp.asarray(get("model.norm.weight"), dt)

    extra = [k for k in sd
             if k not in consumed and "rotary_emb.inv_freq" not in k]
    if extra:
        raise ValueError(
            f"{len(extra)} checkpoint tensor(s) were not consumed — the "
            f"config does not describe this checkpoint (wrong n_layers? "
            f"an architecture with biases?).  First few: "
            f"{sorted(extra)[:4]}")
    return {
        "embed": embed,
        "layers": layers,
        "final_norm": norm,
        "lm_head": head,
    }


def to_hf_state_dict(params: Dict, cfg: LlamaConfig,
                     tied_embeddings: bool = False
                     ) -> Dict[str, np.ndarray]:
    """The inverse mapping (round-trip tested): this repo's pytree back to
    HF naming/orientation — for exporting fine-tuned weights.
    ``tied_embeddings=True`` omits ``lm_head.weight`` (the
    tie_word_embeddings checkpoint shape from_hf_state_dict accepts)."""
    if cfg.pp_axis:
        raise ValueError("export from the stacked pp layout is not "
                         "supported; rebuild params with pp_axis=None")
    if cfg.n_experts:
        raise ValueError("to_hf_state_dict export for the MoE/Mixtral "
                         "layout (block_sparse_moe.*) is not yet "
                         "implemented — only the dense Llama/Mistral "
                         "shape exports; import via from_hf_state_dict "
                         "supports both")
    sd: Dict[str, np.ndarray] = {
        "model.embed_tokens.weight": np.asarray(params["embed"],
                                                np.float32),
        "model.norm.weight": np.asarray(params["final_norm"], np.float32),
    }
    if tied_embeddings:
        # Refuse to silently drop a head that diverged from the
        # embedding (fine-tuning breaks the tie).
        if not np.allclose(np.asarray(params["lm_head"], np.float32),
                           np.asarray(params["embed"], np.float32).T,
                           atol=1e-6):
            raise ValueError(
                "tied_embeddings=True but params['lm_head'] != "
                "embed.T — exporting would discard trained head "
                "weights; export untied instead")
    else:
        sd["lm_head.weight"] = np.asarray(params["lm_head"],
                                          np.float32).T
    for i, lp in enumerate(params["layers"]):
        pre = f"model.layers.{i}."
        sd[pre + "input_layernorm.weight"] = np.asarray(
            lp["attn_norm"], np.float32)
        sd[pre + "post_attention_layernorm.weight"] = np.asarray(
            lp["mlp_norm"], np.float32)
        sd[pre + "self_attn.q_proj.weight"] = np.asarray(
            lp["wq"], np.float32).T
        sd[pre + "self_attn.k_proj.weight"] = np.asarray(
            lp["wk"], np.float32).T
        sd[pre + "self_attn.v_proj.weight"] = np.asarray(
            lp["wv"], np.float32).T
        sd[pre + "self_attn.o_proj.weight"] = np.asarray(
            lp["wo"], np.float32).T
        sd[pre + "mlp.gate_proj.weight"] = np.asarray(
            lp["w1"], np.float32).T
        sd[pre + "mlp.up_proj.weight"] = np.asarray(
            lp["w3"], np.float32).T
        sd[pre + "mlp.down_proj.weight"] = np.asarray(
            lp["w2"], np.float32).T
    return sd
