"""GPT-2 decoder family (learned positions, pre-LN, tied LM head).

Beyond the reference's model zoo (Horovod ships only wrapper examples —
SURVEY.md P14): the third transformer family, covering the architecture
axis llama does not — learned positional embeddings instead of rope,
LayerNorm with biases instead of RMSNorm, biased projections, tanh-GELU,
and a vocabulary-tied LM head.  Causal attention rides the same
routing as llama (`resolve_flash(..., causal=True)` → the Pallas flash
kernels on TPU at/past the measured crossover).

Sharding: dp over the batch, Megatron tp through attention and MLP
(column-split q/k/v and w_in with their biases, row-split wo/w_out with
a psum and replicated output biases).  Embeddings, layernorms and the
tied head are replicated.  Sequence parallelism is not wired for this
family (use llama for long context).

``from_hf_state_dict`` maps HuggingFace ``GPT2LMHeadModel`` weights
onto this pytree; HF's ``Conv1D`` stores ``[in, out]`` exactly like
this module's ``x @ W`` convention, so conversion is a fused-qkv split
plus renames — no transposes.  Parity is pinned against ``transformers``
logits in tests.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Mapping, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P
from ..compat import axis_size as compat_axis_size


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    d_model: int = 768           # gpt2 (124M)
    n_layers: int = 12
    n_heads: int = 12
    d_ff: int = 3072
    max_seq: int = 1024
    dtype: Any = jnp.bfloat16
    dp_axis: Optional[str] = "dp"
    tp_axis: Optional[str] = "tp"
    use_flash: Optional[bool] = None
    ln_eps: float = 1e-5

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    def __post_init__(self):
        if self.d_model % self.n_heads:
            raise ValueError("d_model must divide by n_heads")


def gpt2() -> GPT2Config:
    return GPT2Config()


def tiny(**kw) -> GPT2Config:
    defaults = dict(vocab_size=256, d_model=64, n_layers=2, n_heads=4,
                    d_ff=128, max_seq=64)
    defaults.update(kw)
    return GPT2Config(**defaults)


def init_params(cfg: GPT2Config, key) -> Dict:
    k = iter(jax.random.split(key, 3 + 6 * cfg.n_layers))
    D, H, Hd, F = cfg.d_model, cfg.n_heads, cfg.head_dim, cfg.d_ff
    dt = cfg.dtype

    def dense(key, fan_in, shape):
        return (jax.random.normal(key, shape, jnp.float32)
                * (1.0 / np.sqrt(fan_in))).astype(dt)

    layers = []
    for _ in range(cfg.n_layers):
        layers.append({
            "ln1_scale": jnp.ones((D,), dt), "ln1_bias": jnp.zeros((D,), dt),
            "wq": dense(next(k), D, (D, H * Hd)),
            "bq": jnp.zeros((H * Hd,), dt),
            "wk": dense(next(k), D, (D, H * Hd)),
            "bk": jnp.zeros((H * Hd,), dt),
            "wv": dense(next(k), D, (D, H * Hd)),
            "bv": jnp.zeros((H * Hd,), dt),
            "wo": dense(next(k), H * Hd, (H * Hd, D)),
            "bo": jnp.zeros((D,), dt),
            "ln2_scale": jnp.ones((D,), dt), "ln2_bias": jnp.zeros((D,), dt),
            "w_in": dense(next(k), D, (D, F)), "b_in": jnp.zeros((F,), dt),
            "w_out": dense(next(k), F, (F, D)), "b_out": jnp.zeros((D,), dt),
        })
    return {
        "wte": dense(next(k), D, (cfg.vocab_size, D)),
        "wpe": dense(next(k), D, (cfg.max_seq, D)),
        "layers": layers,
        "lnf_scale": jnp.ones((D,), dt),
        "lnf_bias": jnp.zeros((D,), dt),
        # LM head is TIED to wte (logits = x @ wte.T) — no extra param.
    }


def param_specs(cfg: GPT2Config) -> Dict:
    tp = cfg.tp_axis
    layer = {
        "ln1_scale": P(), "ln1_bias": P(),
        "wq": P(None, tp), "bq": P(tp),
        "wk": P(None, tp), "bk": P(tp),
        "wv": P(None, tp), "bv": P(tp),
        "wo": P(tp, None), "bo": P(),
        "ln2_scale": P(), "ln2_bias": P(),
        "w_in": P(None, tp), "b_in": P(tp),
        "w_out": P(tp, None), "b_out": P(),
    }
    return {
        "wte": P(), "wpe": P(),
        "layers": [dict(layer) for _ in range(cfg.n_layers)],
        "lnf_scale": P(), "lnf_bias": P(),
    }


def _layernorm(x, scale, bias, eps):
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    return ((xf - mu) * lax.rsqrt(var + eps)).astype(x.dtype) * scale + bias


def _attention(x, p, cfg: GPT2Config):
    from ..ops.flash_attention import flash_attention, resolve_flash
    from ..parallel.ring_attention import local_flash_attention

    B, T, D = x.shape
    tp = compat_axis_size(cfg.tp_axis) if cfg.tp_axis else 1
    if cfg.n_heads % tp:
        raise ValueError(f"n_heads={cfg.n_heads} not divisible by tp={tp}")
    H_loc, Hd = cfg.n_heads // tp, cfg.head_dim
    q = (x @ p["wq"] + p["bq"]).reshape(B, T, H_loc, Hd)
    k = (x @ p["wk"] + p["bk"]).reshape(B, T, H_loc, Hd)
    v = (x @ p["wv"] + p["bv"]).reshape(B, T, H_loc, Hd)
    if resolve_flash(cfg.use_flash, seq=T, causal=True):
        out = flash_attention(q, k, v, causal=True)
    else:
        out = local_flash_attention(q, k, v, causal=True)
    out = out.reshape(B, T, H_loc * Hd) @ p["wo"]
    if cfg.tp_axis:
        out = lax.psum(out, cfg.tp_axis)
    return out + p["bo"]


def _mlp(x, p, cfg: GPT2Config):
    # GPT-2's activation is the tanh-approximate GELU ("gelu_new").
    h = jax.nn.gelu(x @ p["w_in"] + p["b_in"], approximate=True)
    out = h @ p["w_out"]
    if cfg.tp_axis:
        out = lax.psum(out, cfg.tp_axis)
    return out + p["b_out"]


def forward(params, tokens, cfg: GPT2Config):
    """Logits [B_loc, T, vocab] for the local token shard (tied head)."""
    B, T = tokens.shape
    x = params["wte"][tokens] + params["wpe"][jnp.arange(T)][None]
    x = x.astype(cfg.dtype)
    for p in params["layers"]:
        x = x + _attention(
            _layernorm(x, p["ln1_scale"], p["ln1_bias"], cfg.ln_eps), p, cfg)
        x = x + _mlp(
            _layernorm(x, p["ln2_scale"], p["ln2_bias"], cfg.ln_eps), p, cfg)
    x = _layernorm(x, params["lnf_scale"], params["lnf_bias"], cfg.ln_eps)
    return (x @ params["wte"].T).astype(jnp.float32)


def loss_fn(params, tokens, targets, cfg: GPT2Config):
    """Partial causal-LM loss (sum semantics — see bert.mlm_loss_fn):
    global-token denominator psum'd over dp, times tp for the redundant
    tensor-parallel compute."""
    logits = forward(params, tokens, cfg)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    local_sum = jnp.sum(nll)
    denom = jnp.asarray(tokens.shape[0] * tokens.shape[1], jnp.float32)
    if cfg.dp_axis:
        denom = lax.psum(denom, cfg.dp_axis)
    if cfg.tp_axis:
        denom = denom * compat_axis_size(cfg.tp_axis)
    return local_sum / denom


def psum_loss(loss_partial, cfg: GPT2Config):
    for ax in (cfg.dp_axis, cfg.tp_axis):
        if ax:
            loss_partial = lax.psum(loss_partial, ax)
    return loss_partial


def sync_grads(grads, cfg: GPT2Config, specs=None):
    specs = specs or param_specs(cfg)

    def leaf_sync(g, spec):
        if cfg.dp_axis:
            g = lax.psum(g, cfg.dp_axis)
        if cfg.tp_axis and all(s != cfg.tp_axis for s in spec):
            g = lax.psum(g, cfg.tp_axis)
        return g

    return jax.tree_util.tree_map(leaf_sync, grads, specs,
                                  is_leaf=lambda x: isinstance(x, P))


def make_train_step(cfg: GPT2Config, optimizer):
    import optax

    def step(params, opt_state, tokens, targets):
        loss_partial, grads = jax.value_and_grad(loss_fn)(
            params, tokens, targets, cfg)
        grads = sync_grads(grads, cfg)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, psum_loss(loss_partial, cfg)

    return step


# ------------------------------------------------------------- HF convert
def _np_arr(x) -> np.ndarray:
    if hasattr(x, "detach"):          # torch.Tensor, without importing torch
        x = x.detach().cpu()
        if str(x.dtype) == "torch.bfloat16":
            x = x.float()
        x = x.numpy()
    return np.asarray(x)


def from_hf_state_dict(sd: Mapping[str, Any], cfg: GPT2Config) -> Dict:
    """HuggingFace ``GPT2LMHeadModel`` state dict -> this pytree.

    HF's ``Conv1D`` stores weights ``[in, out]`` (x @ W + b), matching
    this module — the only structural work is splitting the fused
    ``attn.c_attn`` ``[D, 3D]`` into wq/wk/wv (+biases).  Keys may carry
    the ``transformer.`` prefix (GPT2LMHeadModel) or not (GPT2Model).
    """
    dt = cfg.dtype
    pref = "transformer." if any(k.startswith("transformer.") for k in sd) \
        else ""

    def get(name):
        return _np_arr(sd[pref + name])

    D = cfg.d_model
    layers = []
    for i in range(cfg.n_layers):
        b = f"h.{i}."
        ca_w = get(b + "attn.c_attn.weight")      # [D, 3D]
        ca_b = get(b + "attn.c_attn.bias")        # [3D]
        wq, wk, wv = np.split(ca_w, 3, axis=1)
        bq, bk, bv = np.split(ca_b, 3, axis=0)
        layers.append({
            "ln1_scale": jnp.asarray(get(b + "ln_1.weight"), dt),
            "ln1_bias": jnp.asarray(get(b + "ln_1.bias"), dt),
            "wq": jnp.asarray(wq, dt), "bq": jnp.asarray(bq, dt),
            "wk": jnp.asarray(wk, dt), "bk": jnp.asarray(bk, dt),
            "wv": jnp.asarray(wv, dt), "bv": jnp.asarray(bv, dt),
            "wo": jnp.asarray(get(b + "attn.c_proj.weight"), dt),
            "bo": jnp.asarray(get(b + "attn.c_proj.bias"), dt),
            "ln2_scale": jnp.asarray(get(b + "ln_2.weight"), dt),
            "ln2_bias": jnp.asarray(get(b + "ln_2.bias"), dt),
            "w_in": jnp.asarray(get(b + "mlp.c_fc.weight"), dt),
            "b_in": jnp.asarray(get(b + "mlp.c_fc.bias"), dt),
            "w_out": jnp.asarray(get(b + "mlp.c_proj.weight"), dt),
            "b_out": jnp.asarray(get(b + "mlp.c_proj.bias"), dt),
        })
    wte = get("wte.weight")
    if wte.shape != (cfg.vocab_size, D):
        raise ValueError(f"wte {wte.shape} != config "
                         f"({cfg.vocab_size}, {D})")
    return {
        "wte": jnp.asarray(wte, dt),
        "wpe": jnp.asarray(get("wpe.weight")[:cfg.max_seq], dt),
        "layers": layers,
        "lnf_scale": jnp.asarray(get("ln_f.weight"), dt),
        "lnf_bias": jnp.asarray(get("ln_f.bias"), dt),
    }


# ------------------------------------------------------------- serving
def init_cache(cfg: GPT2Config, batch: int,
               max_seq: Optional[int] = None):
    """Per-layer KV cache [B, T_max, H, Hd] (single-device serving; the
    tp-sharded and rolling variants live in the flagship llama family)."""
    T = max_seq or cfg.max_seq
    shape = (batch, T, cfg.n_heads, cfg.head_dim)
    return [{"k": jnp.zeros(shape, cfg.dtype),
             "v": jnp.zeros(shape, cfg.dtype)} for _ in range(cfg.n_layers)]


def decode_step(params, cache, tokens, pos, cfg: GPT2Config):
    """One cached decode step: ``tokens [B]`` at position ``pos`` (traced
    scalar) -> (logits [B, vocab], updated cache).  Attention over the
    cache is a masked einsum — at Tq=1 there is no score tile to stream,
    so flash buys nothing (same analysis as llama.decode_step)."""
    if cfg.dp_axis or cfg.tp_axis:
        raise ValueError("gpt2 decode is single-device; build the config "
                         "with dp_axis=None, tp_axis=None")
    B = tokens.shape[0]
    T = cache[0]["k"].shape[1]
    x = (params["wte"][tokens] + params["wpe"][pos][None]).astype(cfg.dtype)
    valid = (jnp.arange(T) <= pos)[None, None, :]        # [1, 1, T]
    new_cache = []
    for p, c in zip(params["layers"], cache):
        h = _layernorm(x, p["ln1_scale"], p["ln1_bias"], cfg.ln_eps)
        q = (h @ p["wq"] + p["bq"]).reshape(B, cfg.n_heads, cfg.head_dim)
        k1 = (h @ p["wk"] + p["bk"]).reshape(B, 1, cfg.n_heads,
                                             cfg.head_dim)
        v1 = (h @ p["wv"] + p["bv"]).reshape(B, 1, cfg.n_heads,
                                             cfg.head_dim)
        ck = lax.dynamic_update_slice(c["k"], k1.astype(c["k"].dtype),
                                      (0, pos, 0, 0))
        cv = lax.dynamic_update_slice(c["v"], v1.astype(c["v"].dtype),
                                      (0, pos, 0, 0))
        new_cache.append({"k": ck, "v": cv})
        s = jnp.einsum("bhd,bthd->bht", q.astype(jnp.float32),
                       ck.astype(jnp.float32)) / np.sqrt(cfg.head_dim)
        s = jnp.where(valid, s, -1e30)
        w = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bht,bthd->bhd", w, cv.astype(jnp.float32))
        o = o.reshape(B, cfg.n_heads * cfg.head_dim).astype(cfg.dtype)
        att = o @ p["wo"] + p["bo"]
        x = x + att
        h2 = _layernorm(x, p["ln2_scale"], p["ln2_bias"], cfg.ln_eps)
        x = x + _mlp(h2, p, cfg)
    x = _layernorm(x, params["lnf_scale"], params["lnf_bias"], cfg.ln_eps)
    return (x @ params["wte"].T).astype(jnp.float32), new_cache


def generate(params, prompt, n_tokens: int, cfg: GPT2Config,
             max_seq: Optional[int] = None):
    """Greedy generation: prompt [B, T0] -> [B, n_tokens] (jit-compatible;
    the whole loop is one lax.scan on device)."""
    B, T0 = prompt.shape
    T = max_seq or (T0 + n_tokens)
    cache = init_cache(cfg, B, T)

    def feed(carry, i):
        tok, cache = carry
        logits, cache = decode_step(params, cache, tok, i, cfg)
        return (jnp.argmax(logits, axis=-1).astype(jnp.int32), cache), None

    # Prefill: feed prompt tokens sequentially through the cache (the
    # minimal variant; the blockwise-flash prefill lives in llama).
    carry = (prompt[:, 0], cache)
    for i in range(1, T0):
        (nxt, cache) = feed((prompt[:, i - 1], carry[1]),
                            jnp.asarray(i - 1))[0]
        carry = (prompt[:, i], cache)
    first, cache = feed((prompt[:, T0 - 1], carry[1]),
                        jnp.asarray(T0 - 1))[0]

    def body(carry, i):
        tok, cache = carry
        logits, cache = decode_step(params, cache, tok, i, cfg)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return (nxt, cache), tok

    (_, _), toks = lax.scan(body, (first, cache),
                            T0 + jnp.arange(n_tokens))
    return jnp.moveaxis(toks, 0, 1)                      # [B, n_tokens]


def to_hf_state_dict(params: Dict, cfg: GPT2Config,
                     prefix: str = "transformer.") -> Dict[str, np.ndarray]:
    """This pytree -> HuggingFace ``GPT2LMHeadModel`` naming (numpy
    float32).  Exact inverse of :func:`from_hf_state_dict` (the fused
    c_attn re-concatenates); includes the tied ``lm_head.weight``."""
    sd: Dict[str, np.ndarray] = {}

    def put(name, arr):
        sd[prefix + name] = np.asarray(arr, np.float32)

    put("wte.weight", params["wte"])
    put("wpe.weight", params["wpe"])
    for i, p in enumerate(params["layers"]):
        b = f"h.{i}."
        put(b + "ln_1.weight", p["ln1_scale"])
        put(b + "ln_1.bias", p["ln1_bias"])
        put(b + "attn.c_attn.weight",
            np.concatenate([np.asarray(p["wq"], np.float32),
                            np.asarray(p["wk"], np.float32),
                            np.asarray(p["wv"], np.float32)], axis=1))
        put(b + "attn.c_attn.bias",
            np.concatenate([np.asarray(p["bq"], np.float32),
                            np.asarray(p["bk"], np.float32),
                            np.asarray(p["bv"], np.float32)], axis=0))
        put(b + "attn.c_proj.weight", p["wo"])
        put(b + "attn.c_proj.bias", p["bo"])
        put(b + "ln_2.weight", p["ln2_scale"])
        put(b + "ln_2.bias", p["ln2_bias"])
        put(b + "mlp.c_fc.weight", p["w_in"])
        put(b + "mlp.c_fc.bias", p["b_in"])
        put(b + "mlp.c_proj.weight", p["w_out"])
        put(b + "mlp.c_proj.bias", p["b_out"])
    put("ln_f.weight", params["lnf_scale"])
    put("ln_f.bias", params["lnf_bias"])
    sd["lm_head.weight"] = np.asarray(params["wte"], np.float32)  # tied
    return sd
