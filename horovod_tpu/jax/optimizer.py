"""JAX binding: DistributedOptimizer / DistributedGradientTape /
broadcast_parameters.

Parity targets in the reference (SURVEY.md §2b P2/P4, §3.2/§3.5):

- ``hvd.DistributedOptimizer`` (``horovod/torch/optimizer.py``,
  ``horovod/tensorflow/__init__.py``): wraps an optimizer so gradients are
  averaged across ranks before the update, with ``backward_passes_per_step``
  local aggregation and optional compression.
- ``hvd.DistributedGradientTape`` (``horovod/tensorflow/__init__.py``):
  wraps gradient computation itself.
- ``broadcast_parameters`` / ``broadcast_optimizer_state``
  (``horovod/torch/functions.py``): rank-0 state sync at start.

TPU-first design: the JAX optimizer is an **optax gradient transformation**.
Inside a jitted, shard_map'ped train step the allreduce is an in-graph
``lax.psum`` over the data-parallel mesh axis — XLA fuses and schedules it
over ICI, which is the whole point of the rebuild (SURVEY.md §7 step 3).
Outside any mesh context it degrades to the identity (world of 1), so the
same training script runs unmodified on one chip.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax
from jax import lax
from ..compat import axis_size as compat_axis_size

from .compression import Compression
from ..ops import collectives as C
from ..common.process_sets import ProcessSet


def _axis_in_scope(axis_name) -> bool:
    """True when `axis_name` is bound by an enclosing shard_map/pmap trace."""
    try:
        compat_axis_size(axis_name)
        return True
    except NameError:
        return False
    except Exception:
        return False


def allreduce_gradients(grads, op: C.ReduceOp = C.ReduceOp.AVERAGE,
                        axis_name: str = C.DEFAULT_AXIS,
                        compression=Compression.none,
                        process_set: Optional[ProcessSet] = None):
    """Tree-allreduce a gradient pytree.

    Two modes, matching how the training step was written:

    - **In-graph** (inside a ``shard_map``/``pmap`` that binds ``axis_name``):
      one fused ``lax.psum`` over all leaves (XLA combines them into a single
      collective — the compiler-native tensor fusion, reference N7).
    - **Eager, per-process** (torovodrun-launched, called outside any mesh
      context): one fused grouped allreduce through the collective engine —
      the reference's hook→background-thread path (SURVEY §3.2).

    Either way compress → reduce → decompress mirrors the reference's hook
    pipeline.  Calling this from a plain ``jax.jit`` trace in a multi-process
    world is an error (a bare jit binds no mesh axis, so the reduce would
    silently be the identity and replicas would diverge) — compute gradients
    under jit but reduce/update eagerly, or use a ``shard_map`` step.
    """
    if process_set is not None:
        axis_name = process_set.axis_name
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    if _axis_in_scope(axis_name):
        comp = [compression.compress(g) for g in leaves]
        reduced = C.grouped_allreduce([c[0] for c in comp], op=op,
                                      axis_name=axis_name)
        out = [compression.decompress(r, c[1]) for r, c in zip(reduced, comp)]
        return jax.tree_util.tree_unflatten(treedef, out)

    from ..ops import eager
    if not eager.per_process_mode():
        return grads  # single-controller SPMD: params/grads already global
    if any(isinstance(l, jax.core.Tracer) for l in leaves):
        raise RuntimeError(
            "allreduce_gradients was traced under jax.jit without a bound "
            f"mesh axis {axis_name!r} in a multi-process world: the reduce "
            "would silently be a no-op and replicas would diverge. Either "
            "compute gradients inside jit but call allreduce_gradients / "
            "DistributedOptimizer.update eagerly (outside jit), or write the "
            "train step with shard_map over the device mesh so the axis is "
            "bound (see models.mnist.make_sharded_train_step).")
    # Eager engine path: fused, device-resident, negotiated across processes.
    # Reverse-registration priority: leaf 0 (the earliest-registered layer,
    # the one the next forward pass touches first) drains first even though
    # backprop produces its gradient last — ByteScheduler-style priority
    # scheduling through the engine's priority queue.  Pytree flatten order
    # is identical on every rank, so the stamps agree.
    prios = [len(leaves) - i for i in range(len(leaves))]
    wire = getattr(compression, "wire_mode", None)
    if wire is not None:
        # Cast-style compression rides INSIDE the fused program (cast-down
        # before the psum, cast-up after): results come back in the
        # gradients' own dtype with half the wire bytes and no extra
        # launches.
        arrs = [jnp.asarray(g) for g in leaves]
        reduced = eager.grouped_allreduce(arrs, op=op,
                                          name="allreduce_gradients",
                                          process_set=process_set,
                                          compression=wire,
                                          priorities=prios)
        out = [jnp.asarray(eager.to_local(r)).reshape(a.shape)
               .astype(a.dtype) for r, a in zip(reduced, arrs)]
        return jax.tree_util.tree_unflatten(treedef, out)
    comp = [compression.compress(jnp.asarray(g)) for g in leaves]
    reduced = eager.grouped_allreduce([c[0] for c in comp], op=op,
                                      name="allreduce_gradients",
                                      process_set=process_set,
                                      priorities=prios)
    reduced = [jnp.asarray(eager.to_local(r)).reshape(c[0].shape)
               .astype(c[0].dtype) for r, c in zip(reduced, comp)]
    out = [compression.decompress(r, c[1]) for r, c in zip(reduced, comp)]
    return jax.tree_util.tree_unflatten(treedef, out)


class _DistOptState(NamedTuple):
    inner_state: Any
    acc: Any                 # gradient accumulator (backward_passes_per_step)
    counter: jnp.ndarray


def DistributedOptimizer(optimizer: optax.GradientTransformation,
                         named_parameters=None,
                         compression=Compression.none,
                         op: C.ReduceOp = C.ReduceOp.AVERAGE,
                         backward_passes_per_step: int = 1,
                         axis_name: str = C.DEFAULT_AXIS,
                         process_set: Optional[ProcessSet] = None,
                         check=False,
                         ) -> optax.GradientTransformation:
    """Wrap an optax optimizer with cross-rank gradient averaging.

    Usage (inside a shard_map/pjit train step over the ``hvd`` axis):

        opt = hvd.DistributedOptimizer(optax.adam(1e-3))
        updates, opt_state = opt.update(grads, opt_state, params)

    ``backward_passes_per_step > 1`` reproduces the reference's gradient
    aggregation (``horovod/tensorflow/gradient_aggregation.py``): gradients
    accumulate locally and the (single) allreduce happens every k-th step.
    ``named_parameters`` is accepted for API parity and unused (pytrees are
    self-describing).

    ``check=True`` lints the calling script for deadlock-prone collective
    patterns at wrap time (``check="strict"`` raises on errors) — see
    ``horovod_tpu.analysis`` and docs/analysis.md.
    """
    del named_parameters
    if check:
        from ..analysis.hooks import run_check_hook
        run_check_hook(check)
    if process_set is not None:
        axis_name = process_set.axis_name
    k = backward_passes_per_step

    def init_fn(params):
        inner = optimizer.init(params)
        if k == 1:
            return _DistOptState(inner, (), jnp.zeros((), jnp.int32))
        acc = jax.tree_util.tree_map(jnp.zeros_like, params)
        return _DistOptState(inner, acc, jnp.zeros((), jnp.int32))

    def _reduce(grads):
        return allreduce_gradients(grads, op=op, axis_name=axis_name,
                                   compression=compression,
                                   process_set=process_set)

    def update_fn(grads, state: _DistOptState, params=None):
        if k == 1:
            updates, inner = optimizer.update(_reduce(grads), state.inner_state,
                                              params)
            return updates, _DistOptState(inner, (), state.counter + 1)

        acc = jax.tree_util.tree_map(lambda a, g: a + g, state.acc, grads)
        counter = state.counter + 1
        apply_now = (counter % k) == 0

        def _do_apply_concrete(acc_, inner_):
            mean_acc = jax.tree_util.tree_map(lambda a: a / k, acc_)
            updates, new_inner = optimizer.update(_reduce(mean_acc), inner_,
                                                  params)
            zeroed = jax.tree_util.tree_map(jnp.zeros_like, acc_)
            return updates, new_inner, zeroed

        # Eager per-process calls must NOT go through lax.cond: it traces
        # both branches, which would trace the engine allreduce.  With a
        # concrete counter a plain Python branch is exact.
        if not isinstance(apply_now, jax.core.Tracer):
            if bool(apply_now):
                updates, inner, acc = _do_apply_concrete(acc, state.inner_state)
            else:
                updates = jax.tree_util.tree_map(jnp.zeros_like, acc)
                inner = state.inner_state
            return updates, _DistOptState(inner, acc, counter)

        def do_apply(operand):
            acc_, inner_ = operand
            mean_acc = jax.tree_util.tree_map(lambda a: a / k, acc_)
            updates, new_inner = optimizer.update(_reduce(mean_acc), inner_,
                                                  params)
            zeroed = jax.tree_util.tree_map(jnp.zeros_like, acc_)
            return updates, new_inner, zeroed

        def skip(operand):
            acc_, inner_ = operand
            updates = jax.tree_util.tree_map(jnp.zeros_like, acc_)
            return updates, inner_, acc_

        updates, inner, acc = lax.cond(apply_now, do_apply, skip,
                                       (acc, state.inner_state))
        return updates, _DistOptState(inner, acc, counter)

    return optax.GradientTransformation(init_fn, update_fn)


def DistributedGradientTape(grad_fn: Callable,
                            compression=Compression.none,
                            op: C.ReduceOp = C.ReduceOp.AVERAGE,
                            axis_name: str = C.DEFAULT_AXIS,
                            process_set: Optional[ProcessSet] = None) -> Callable:
    """Wrap a gradient function so its output gradients are allreduced.

    The JAX rendering of ``hvd.DistributedGradientTape`` (reference
    ``horovod/tensorflow/__init__.py`` §3.5): pass ``jax.grad(loss_fn)`` or
    ``jax.value_and_grad(loss_fn)``; the wrapper averages whatever gradient
    pytree comes back.  Works with ``value_and_grad`` by reducing only the
    gradient half of the result.
    """
    def wrapped(*args, **kwargs):
        out = grad_fn(*args, **kwargs)
        if isinstance(out, tuple) and len(out) == 2:
            value, grads = out
            return value, allreduce_gradients(
                grads, op=op, axis_name=axis_name, compression=compression,
                process_set=process_set)
        return allreduce_gradients(out, op=op, axis_name=axis_name,
                                   compression=compression,
                                   process_set=process_set)
    return wrapped


def broadcast_parameters(params, root_rank: int = 0,
                         process_set: Optional[ProcessSet] = None):
    """Synchronize a parameter pytree from ``root_rank`` to all ranks.

    Reference: ``horovod/torch/functions.py broadcast_parameters``.  In
    single-controller SPMD there is exactly one copy of the params (a global
    ``jax.Array``), so all "ranks" are synchronized by construction and this
    is the identity.  In multi-process mode each process holds its own copy
    and the byte-level broadcast runs through the coordinator.
    """
    if jax.process_count() == 1:
        return params
    from ..ops import eager
    out = eager.broadcast_pytree(params, root_rank=root_rank,
                                 process_set=process_set)
    return jax.tree_util.tree_map(jnp.asarray, out)


def broadcast_optimizer_state(opt_state, root_rank: int = 0,
                              process_set: Optional[ProcessSet] = None):
    """Reference: ``horovod/torch/functions.py broadcast_optimizer_state``."""
    return broadcast_parameters(opt_state, root_rank=root_rank,
                                process_set=process_set)
