"""JAX binding: DistributedOptimizer / DistributedGradientTape /
broadcast_parameters.

Parity targets in the reference (SURVEY.md §2b P2/P4, §3.2/§3.5):

- ``hvd.DistributedOptimizer`` (``horovod/torch/optimizer.py``,
  ``horovod/tensorflow/__init__.py``): wraps an optimizer so gradients are
  averaged across ranks before the update, with ``backward_passes_per_step``
  local aggregation and optional compression.
- ``hvd.DistributedGradientTape`` (``horovod/tensorflow/__init__.py``):
  wraps gradient computation itself.
- ``broadcast_parameters`` / ``broadcast_optimizer_state``
  (``horovod/torch/functions.py``): rank-0 state sync at start.

TPU-first design: the JAX optimizer is an **optax gradient transformation**.
Inside a jitted, shard_map'ped train step the allreduce is an in-graph
``lax.psum`` over the data-parallel mesh axis — XLA fuses and schedules it
over ICI, which is the whole point of the rebuild (SURVEY.md §7 step 3).
Outside any mesh context it degrades to the identity (world of 1), so the
same training script runs unmodified on one chip.
"""

from __future__ import annotations

from typing import Any, Callable, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from ..compat import axis_size as compat_axis_size

from .compression import Compression
from ..ops import collectives as C
from ..common.process_sets import ProcessSet


def _axis_in_scope(axis_name) -> bool:
    """True when `axis_name` is bound by an enclosing shard_map/pmap trace."""
    try:
        compat_axis_size(axis_name)
        return True
    except NameError:
        return False
    except Exception:
        return False


def allreduce_gradients(grads, op: C.ReduceOp = C.ReduceOp.AVERAGE,
                        axis_name: str = C.DEFAULT_AXIS,
                        compression=Compression.none,
                        process_set: Optional[ProcessSet] = None):
    """Tree-allreduce a gradient pytree.

    Two modes, matching how the training step was written:

    - **In-graph** (inside a ``shard_map``/``pmap`` that binds ``axis_name``):
      one fused ``lax.psum`` over all leaves (XLA combines them into a single
      collective — the compiler-native tensor fusion, reference N7).
    - **Eager, per-process** (torovodrun-launched, called outside any mesh
      context): one fused grouped allreduce through the collective engine —
      the reference's hook→background-thread path (SURVEY §3.2).

    Either way compress → reduce → decompress mirrors the reference's hook
    pipeline.  Calling this from a plain ``jax.jit`` trace in a multi-process
    world is an error (a bare jit binds no mesh axis, so the reduce would
    silently be the identity and replicas would diverge) — compute gradients
    under jit but reduce/update eagerly, or use a ``shard_map`` step.
    """
    if process_set is not None:
        axis_name = process_set.axis_name
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    if _axis_in_scope(axis_name):
        comp = [compression.compress(g) for g in leaves]
        reduced = C.grouped_allreduce([c[0] for c in comp], op=op,
                                      axis_name=axis_name)
        out = [compression.decompress(r, c[1]) for r, c in zip(reduced, comp)]
        return jax.tree_util.tree_unflatten(treedef, out)

    from ..ops import eager
    if not eager.per_process_mode():
        return grads  # single-controller SPMD: params/grads already global
    if any(isinstance(l, jax.core.Tracer) for l in leaves):
        raise RuntimeError(
            "allreduce_gradients was traced under jax.jit without a bound "
            f"mesh axis {axis_name!r} in a multi-process world: the reduce "
            "would silently be a no-op and replicas would diverge. Either "
            "compute gradients inside jit but call allreduce_gradients / "
            "DistributedOptimizer.update eagerly (outside jit), or write the "
            "train step with shard_map over the device mesh so the axis is "
            "bound (see models.mnist.make_sharded_train_step).")
    # Eager engine path: fused, device-resident, negotiated across processes.
    # Reverse-registration priority: leaf 0 (the earliest-registered layer,
    # the one the next forward pass touches first) drains first even though
    # backprop produces its gradient last — ByteScheduler-style priority
    # scheduling through the engine's priority queue.  Pytree flatten order
    # is identical on every rank, so the stamps agree.
    prios = [len(leaves) - i for i in range(len(leaves))]
    wire = getattr(compression, "wire_mode", None)
    if wire is not None:
        # Cast-style compression rides INSIDE the fused program (cast-down
        # before the psum, cast-up after): results come back in the
        # gradients' own dtype with half the wire bytes and no extra
        # launches.
        arrs = [jnp.asarray(g) for g in leaves]
        reduced = eager.grouped_allreduce(arrs, op=op,
                                          name="allreduce_gradients",
                                          process_set=process_set,
                                          compression=wire,
                                          priorities=prios)
        out = [jnp.asarray(eager.to_local(r)).reshape(a.shape)
               .astype(a.dtype) for r, a in zip(reduced, arrs)]
        return jax.tree_util.tree_unflatten(treedef, out)
    comp = [compression.compress(jnp.asarray(g)) for g in leaves]
    reduced = eager.grouped_allreduce([c[0] for c in comp], op=op,
                                      name="allreduce_gradients",
                                      process_set=process_set,
                                      priorities=prios)
    reduced = [jnp.asarray(eager.to_local(r)).reshape(c[0].shape)
               .astype(c[0].dtype) for r, c in zip(reduced, comp)]
    out = [compression.decompress(r, c[1]) for r, c in zip(reduced, comp)]
    return jax.tree_util.tree_unflatten(treedef, out)


class _DistOptState(NamedTuple):
    inner_state: Any
    acc: Any                 # gradient accumulator (backward_passes_per_step)
    counter: jnp.ndarray


# --------------------------------------------------------------------------
# ZeRO-sharded data plane (ISSUE 15): DistributedOptimizer(sharded=True)
# --------------------------------------------------------------------------

class _ShardPlan(NamedTuple):
    """Static sharding plan, fixed at init: a pure function of (leaf
    shapes/dtypes, world, the pipeline-chunk knob), so every rank derives
    the identical bucket structure — bucket membership shapes the wire
    names and digests, which negotiation checks for consistency."""
    world: int
    rank: int
    shapes: Tuple[Tuple[int, ...], ...]     # logical per-leaf shapes
    dtypes: Tuple[str, ...]
    sizes: Tuple[int, ...]                  # logical element counts
    pads: Tuple[int, ...]                   # pad+slice convention pads
    pers: Tuple[int, ...]                   # shard length per leaf
    buckets: Tuple[Tuple[int, ...], ...]    # leaf indices per bucket


class ShardedOptimizerState:
    """Eager ZeRO state: one inner optax state per bucket, every array
    leaf holding only this rank's 1/world shard (HBM/host cost scales
    1/world).  Deliberately NOT a pytree — it lives between eager update
    calls only; the elastic integration goes through
    :meth:`hvd_sharded_saveable` / :func:`load_sharded_saveable`."""

    def __init__(self, inner_states: List, plan: _ShardPlan,
                 process_set: Optional[ProcessSet] = None):
        self.inner_states = list(inner_states)
        self.plan = plan
        # The set the plan's world/rank are relative to: the gather in
        # hvd_sharded_saveable must negotiate over exactly these ranks
        # (a subset-set state gathered over the global world would hang
        # the ranks outside the subset and stack in the wrong order).
        self.process_set = process_set

    def opt_state_bytes(self) -> int:
        """Bytes of optimizer state resident on THIS rank (the 1/N claim
        the bench's ``sharded_ab`` section asserts)."""
        total = 0
        for s in self.inner_states:
            for leaf in jax.tree_util.tree_leaves(s):
                if hasattr(leaf, "nbytes"):
                    total += int(leaf.nbytes)
        return total

    def hvd_sharded_saveable(self, process_set: Optional[ProcessSet] = None):
        """Rank-invariant host representation for elastic commits: every
        sharded array leaf is allgathered to its full padded flat form, so
        all ranks serialize the identical blob (the state plane's shard
        digests require it) and a (re-)joining rank re-slices exactly its
        own 1/N with :func:`load_sharded_saveable`.  ``process_set=None``
        gathers over the set the state was initialized with."""
        from ..ops import eager
        if process_set is None:
            process_set = self.process_set
        if self.plan.world > 1 and not eager.per_process_mode():
            # Emitting this rank's bare shards stamped world=N would be a
            # valid-LOOKING saveable that load_sharded_saveable silently
            # re-slices into 1/N of 1/N — corrupt state.  Fail loudly: a
            # multi-process sharded state can only gather while the
            # engine is live.
            raise RuntimeError(
                "cannot save a DistributedOptimizer(sharded=True) state "
                f"sharded over {self.plan.world} ranks without the live "
                "collective engine (commit before shutdown, not after)")
        gathered = []
        for b, st in enumerate(self.inner_states):
            leaves, treedef = jax.tree_util.tree_flatten(st)
            arrs = [(i, l) for i, l in enumerate(leaves)
                    if getattr(l, "ndim", 0) >= 1]
            if arrs and self.plan.world > 1:
                full = eager.grouped_allgather(
                    [jnp.asarray(l) for _, l in arrs],
                    name=f"sharded_state_gather.b{b}",
                    process_set=process_set, sharded=True)
                for (i, _), f in zip(arrs, full):
                    leaves[i] = np.asarray(eager.to_local(f))
            out = [np.asarray(jax.device_get(l)) for l in leaves]
            gathered.append(jax.tree_util.tree_unflatten(treedef, out))
        return {"__hvd_sharded_opt__": 1, "world": self.plan.world,
                "plan": self.plan._replace(rank=-1)._asdict(),
                "inner_states": gathered}


class FullShardedState(ShardedOptimizerState):
    """Eager ZeRO-3 (FSDP) state: like :class:`ShardedOptimizerState`,
    plus the resident **parameter** shards — ``param_shards[b]`` is the
    tuple of flat 1/world leaves of bucket ``b``, THE authoritative
    parameters (no replicated copy exists between steps).  The training
    loop rematerializes full parameters per step with
    :meth:`gather_params`, whose per-bucket allgathers ride the engine's
    PREFETCH lane ``HOROVOD_PREFETCH_DEPTH`` buckets ahead, so bucket
    k+1's gather overlaps bucket k's consumption.  With FSDP the
    resident shard IS the PR 14 checkpoint shard — commit/restore move
    1/N bytes by construction."""

    def __init__(self, inner_states: List, plan: _ShardPlan,
                 process_set: Optional[ProcessSet] = None,
                 param_shards: Optional[List] = None, treedef=None):
        super().__init__(inner_states, plan, process_set)
        self.param_shards = list(param_shards or [])
        self.treedef = treedef          # params pytree structure; re-stamped
                                        # from grads after a shard-native load

    def params_bytes(self) -> int:
        """Bytes of parameters resident on THIS rank (≈ full/world)."""
        return sum(int(s.nbytes) for shards in self.param_shards
                   for s in shards if hasattr(s, "nbytes"))

    def resident_bytes(self) -> int:
        """Parameters + optimizer state resident on THIS rank — the ≈ 1/N
        claim bench's ``fsdp_ab`` section and the acceptance worker
        assert (small-leaf padding slack allowed)."""
        return self.params_bytes() + self.opt_state_bytes()

    def gather_params(self, depth: Optional[int] = None):
        """Rematerialize the full parameter pytree — the FSDP prefetch
        pipeline.  Buckets ``0..depth-1`` dispatch their allgathers up
        front; then, for each bucket k in order, bucket ``k+depth``'s
        gather is dispatched BEFORE bucket k is synchronized — overlap by
        construction, no timing races.  Each gather group is marked
        ``prefetch=True`` (PREFETCH backlog lane: after FAST, before
        FUSED, budget-exempt) and ``sharded="full"`` (own digest token).
        Gathered buffers belong to the caller and are dropped after the
        step — peak HBM stays shard + the depth-bounded window."""
        from ..ops import eager
        plan = self.plan
        nb = len(plan.buckets)
        nl = len(plan.shapes)
        if depth is None:
            depth = _prefetch_depth()
        depth = max(1, int(depth))
        eng = eager._engine()
        handles: List[Optional[dict]] = [None] * nb

        def dispatch(b: int):
            idxs = plan.buckets[b]
            live = [i for i in idxs if plan.pers[i] > 0]
            shards = [jnp.asarray(s) for s, i in
                      zip(self.param_shards[b], idxs) if plan.pers[i] > 0]
            hs = eager.grouped_allgather_async(
                shards, name=f"fsdp_prefetch.b{b}",
                process_set=self.process_set,
                priorities=[nl - i for i in live],
                sharded="full", prefetch=True) if live else []
            handles[b] = dict(zip(live, hs))
            if b > 0:
                # Dispatched while an earlier bucket's gather is still
                # outstanding — the overlap evidence the acceptance
                # criterion asks for, counted deterministically.
                eng.prefetch_overlapped = \
                    getattr(eng, "prefetch_overlapped", 0) + 1

        for b in range(min(depth, nb)):
            dispatch(b)
        if nb:
            eng.kick()
        out: List[Any] = [None] * nl
        for b in range(nb):
            if b + depth < nb:
                dispatch(b + depth)     # before bucket b synchronizes
                eng.kick()
            for i, h in handles[b].items():
                full = np.asarray(eager.to_local(eager.synchronize(h)))
                full = full.reshape(-1)[:plan.sizes[i]]
                out[i] = jnp.asarray(full.reshape(plan.shapes[i])) \
                    .astype(plan.dtypes[i])
        for i in range(nl):
            if out[i] is None:
                out[i] = jnp.zeros(plan.shapes[i], plan.dtypes[i])
        if self.treedef is None:
            return out
        return jax.tree_util.tree_unflatten(self.treedef, out)

    def hvd_sharded_saveable(self, process_set: Optional[ProcessSet] = None):
        """Rank-invariant saveable: the PR 15 form plus the gathered
        parameter shards, under the ``__hvd_full_sharded__`` marker."""
        from ..ops import eager
        base = super().hvd_sharded_saveable(process_set)
        if process_set is None:
            process_set = self.process_set
        gathered = []
        for b, shards in enumerate(self.param_shards):
            idxs = self.plan.buckets[b]
            live = [(j, s) for j, s in enumerate(shards)
                    if self.plan.pers[idxs[j]] > 0]
            outs = [np.asarray(jax.device_get(s)) for s in shards]
            if live and self.plan.world > 1:
                full = eager.grouped_allgather(
                    [jnp.asarray(s) for _, s in live],
                    name=f"fsdp_param_gather.b{b}",
                    process_set=process_set, sharded="full")
                for (j, _), f in zip(live, full):
                    outs[j] = np.asarray(eager.to_local(f))
            gathered.append(outs)
        base["__hvd_full_sharded__"] = 1
        base["param_shards"] = gathered
        return base


def _prefetch_depth() -> int:
    """The HOROVOD_PREFETCH_DEPTH knob (default 2): how many buckets of
    gathered parameters may be in flight ahead of consumption."""
    from ..common import basics
    cfg = basics._get_state().config
    if cfg is None:
        return 2
    return max(1, int(getattr(cfg, "prefetch_depth", 2) or 2))


def is_sharded_saveable(value) -> bool:
    """True for the marker dict :meth:`hvd_sharded_saveable` produces."""
    return isinstance(value, dict) and value.get("__hvd_sharded_opt__") == 1


def load_sharded_saveable(saved, rank: int, world: int):
    """Rebuild THIS rank's :class:`ShardedOptimizerState` from a recovered
    rank-invariant saveable: each gathered flat leaf ``[world*per]`` is
    re-sliced to the joining rank's own 1/N (``[rank*per, (rank+1)*per)``)
    — the shard-native restore the state plane's peer fetch feeds.
    Returns ``None`` when the committed world size differs (a resized
    fleet re-inits optimizer state instead of guessing a re-shard)."""
    if not is_sharded_saveable(saved) or int(saved["world"]) != int(world) \
            or world < 1:
        return None
    plan = _ShardPlan(**dict(saved["plan"], rank=int(rank)))

    def reslice(leaf):
        arr = np.asarray(leaf)
        if arr.ndim < 1 or arr.size % world:
            return jnp.asarray(arr) if arr.ndim else arr
        per = arr.size // world
        return jnp.asarray(arr.reshape(-1)[rank * per:(rank + 1) * per])

    inner_states = [jax.tree_util.tree_map(reslice, st)
                    for st in saved["inner_states"]]
    if saved.get("__hvd_full_sharded__") == 1:
        # FSDP saveable (ISSUE 18): the gathered parameter shards reslice
        # exactly like the optimizer-state leaves (padded flats are always
        # world-divisible).  The treedef is re-stamped from the first
        # update's gradient tree; gather_params before then returns the
        # flat leaf list.
        param_shards = [tuple(reslice(s) for s in shards)
                        for shards in saved["param_shards"]]
        return FullShardedState(inner_states, plan,
                                param_shards=param_shards)
    return ShardedOptimizerState(inner_states, plan)


def _make_shard_plan(leaves, world: int, rank: int,
                     chunk_bytes: int) -> _ShardPlan:
    from ..parallel.zero import shard_info
    shapes, dtypes, sizes, pads, pers, isizes = [], [], [], [], [], []
    for l in leaves:
        shape = tuple(getattr(l, "shape", ()))
        n = int(np.prod(shape)) if shape else 1
        pad, per = shard_info(n, world)
        dt = jnp.asarray(l).dtype
        shapes.append(shape)
        dtypes.append(str(dt))
        isizes.append(int(dt.itemsize))
        sizes.append(n)
        pads.append(pad)
        pers.append(per)
    # Bucket assignment (HOROVOD_PIPELINE_CHUNK): greedy packing in
    # registration order up to ~chunk bytes of padded payload per bucket,
    # so the scatter of bucket b+1 overlaps the shard update + gather of
    # bucket b.  Knob 0/off = one bucket (the whole tree updates at once;
    # cross-leaf inner transforms then see the full shard tree).
    buckets: List[Tuple[int, ...]] = []
    if chunk_bytes and chunk_bytes > 0:
        cur: List[int] = []
        cur_bytes = 0
        for i in range(len(leaves)):
            b = (sizes[i] + pads[i]) * isizes[i]
            if cur and cur_bytes + b > chunk_bytes:
                buckets.append(tuple(cur))
                cur, cur_bytes = [], 0
            cur.append(i)
            cur_bytes += b
        if cur:
            buckets.append(tuple(cur))
    else:
        buckets = [tuple(range(len(leaves)))] if leaves else []
    return _ShardPlan(world=world, rank=rank, shapes=tuple(shapes),
                      dtypes=tuple(dtypes), sizes=tuple(sizes),
                      pads=tuple(pads), pers=tuple(pers),
                      buckets=tuple(buckets))


def _sharded_world_rank(process_set: Optional[ProcessSet]):
    """(world, this process's rank within the set) for the eager sharded
    path.  One device per process is required: a multi-device process
    would own several shards, and the shard-local inner update below is
    written for exactly one."""
    from ..common import basics
    st = basics._get_state()
    ps = st.process_set_table.get(
        0 if process_set is None or process_set.process_set_id is None
        else process_set.process_set_id)
    mine = [i for i, d in enumerate(ps.mesh.devices.flat)
            if d.process_index == jax.process_index()]
    if len(mine) != 1:
        raise NotImplementedError(
            f"DistributedOptimizer(sharded=True) eager path needs exactly "
            f"one device per process; this process drives {len(mine)}. "
            f"Use the in-graph path (shard_map + parallel.zero."
            f"sharded_optimizer) for multi-device processes.")
    return ps.size(), mine[0]


def _device_shard(x, pad: int, per: int, rank: int):
    flat = jnp.ravel(x)
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat[rank * per:(rank + 1) * per]


def _sharded_eager_init(optimizer, params, process_set, chunk_bytes):
    from ..parallel.zero import shard_slice_host
    leaves, _treedef = jax.tree_util.tree_flatten(params)
    world, rank = _sharded_world_rank(process_set)
    plan = _make_shard_plan(leaves, world, rank, chunk_bytes)
    inner_states = []
    for idxs in plan.buckets:
        shard_params = tuple(
            jnp.asarray(shard_slice_host(jax.device_get(leaves[i]),
                                         rank, world))
            for i in idxs)
        inner_states.append(optimizer.init(shard_params))
    return ShardedOptimizerState(inner_states, plan, process_set)


def _sharded_eager_update(optimizer, grads,
                          state: ShardedOptimizerState, params,
                          op: C.ReduceOp,
                          process_set: Optional[ProcessSet]):
    """The ZeRO pipeline through the engine: per-bucket reduce-scatter of
    fused gradients (each rank receives its 1/N shard — half the wire
    bytes of an allreduce of the same payload), the inner optimizer
    update applied on the shard only, then an allgather of the updated
    parameter deltas.  Every bucket's scatter is in flight before the
    first bucket's update runs, so with HOROVOD_PIPELINE_CHUNK set the
    scatter → update → gather stages overlap across buckets (the engine's
    in-flight window + priority backlog do the interleaving)."""
    from ..ops import eager
    plan = state.plan
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    if tuple(tuple(getattr(l, "shape", ())) for l in leaves) != plan.shapes:
        raise ValueError(
            "gradient tree shapes changed since DistributedOptimizer"
            "(sharded=True) state was initialized; re-init the optimizer "
            "state for the new parameter tree")
    if op not in (C.ReduceOp.AVERAGE, C.ReduceOp.SUM):
        raise ValueError(f"sharded=True supports SUM/AVERAGE, not {op!r}")
    rank, world = plan.rank, plan.world
    nl = len(leaves)

    # Phase 1: every bucket's reduce-scatter goes out BEFORE any update
    # runs — the engine fuses each bucket atomically and the in-flight
    # window keeps later buckets' scatters on the wire while earlier
    # buckets update.  Reverse-registration priorities: the first
    # parameters the next forward pass needs lead each cycle.
    rs_handles: List[dict] = []
    for b, idxs in enumerate(plan.buckets):
        live = [i for i in idxs if plan.pers[i] > 0]   # empty leaves skip
        padded = []
        for i in live:
            flat = jnp.ravel(jnp.asarray(leaves[i]))
            if plan.pads[i]:
                flat = jnp.pad(flat, (0, plan.pads[i]))
            padded.append(flat)
        handles = eager.grouped_reducescatter_async(
            padded, name=f"sharded_rs.b{b}", op=op,
            process_set=process_set,
            priorities=[nl - i for i in live], sharded=True) \
            if padded else []
        rs_handles.append(dict(zip(live, handles)))
    eng = eager._engine()
    eng.kick()

    p_leaves = jax.tree_util.tree_flatten(params)[0] \
        if params is not None else None
    ag_handles: List = []
    new_inner: List = []
    for b, idxs in enumerate(plan.buckets):
        g_shards = tuple(
            jnp.asarray(eager.to_local(
                eager.synchronize(rs_handles[b][i]))).reshape(-1)
            .astype(plan.dtypes[i]) if plan.pers[i] > 0
            else jnp.zeros((0,), plan.dtypes[i])
            for i in idxs)
        p_shards = None
        if p_leaves is not None:
            p_shards = tuple(
                _device_shard(jnp.asarray(p_leaves[i]), plan.pads[i],
                              plan.pers[i], rank) for i in idxs)
        updates_b, inner_b = optimizer.update(
            g_shards, state.inner_states[b], p_shards)
        new_inner.append(inner_b)
        # Phase 3 (overlapped): this bucket's updated deltas start their
        # allgather while later buckets are still scattering/updating.
        live = [i for i in idxs if plan.pers[i] > 0]
        handles = eager.grouped_allgather_async(
            [jnp.asarray(u) for u, i in zip(updates_b, idxs) if i in live],
            name=f"sharded_ag.b{b}", process_set=process_set,
            priorities=[nl - i for i in live], sharded=True) \
            if live else []
        ag_handles.append(dict(zip(live, handles)))
        eng.kick()

    out: List[Any] = [None] * nl
    for b, idxs in enumerate(plan.buckets):
        for i in idxs:
            if plan.pers[i] == 0:
                out[i] = jnp.zeros(plan.shapes[i], plan.dtypes[i])
                continue
            full = np.asarray(eager.to_local(
                eager.synchronize(ag_handles[b][i])))
            full = full.reshape(-1)[:plan.sizes[i]]
            out[i] = jnp.asarray(full.reshape(plan.shapes[i])) \
                .astype(plan.dtypes[i])
    updates = jax.tree_util.tree_unflatten(treedef, out)
    return updates, ShardedOptimizerState(new_inner, plan, process_set)


def _full_sharded_eager_init(optimizer, params, process_set, chunk_bytes):
    """FSDP init: slice parameters into this rank's per-bucket shards and
    init the inner optimizer ON the shards.  The full (replicated)
    ``params`` tree the caller passed may be dropped afterwards — the
    shards are the resident truth from here on."""
    from ..parallel.zero import shard_slice_host
    leaves, treedef = jax.tree_util.tree_flatten(params)
    world, rank = _sharded_world_rank(process_set)
    plan = _make_shard_plan(leaves, world, rank, chunk_bytes)
    inner_states, param_shards = [], []
    for idxs in plan.buckets:
        shards = tuple(
            jnp.asarray(shard_slice_host(jax.device_get(leaves[i]),
                                         rank, world))
            for i in idxs)
        inner_states.append(optimizer.init(shards))
        param_shards.append(shards)
    return FullShardedState(inner_states, plan, process_set,
                            param_shards, treedef)


def _full_sharded_eager_update(optimizer, grads, state: FullShardedState,
                               op: C.ReduceOp,
                               process_set: Optional[ProcessSet]):
    """The FSDP backward half: per-bucket **reduce-scatter straight into
    the owning 1/N shard** (no replicated gradient ever exists — the
    engine's scatter output IS the shard), shard-local inner update with
    the RESIDENT parameter shards, and the shards advance in place.

    Returns ``(None, new_state)``: there is no replicated update tree to
    apply because there are no replicated parameters — the forward half
    (:meth:`FullShardedState.gather_params`) rematerializes them next
    step through the prefetch lane.  Wire per step is therefore
    RS(grads) + AG(params) — byte-equal to the PR 15 sharded path's
    RS + delta-AG."""
    from ..ops import eager
    plan = state.plan
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    if tuple(tuple(getattr(l, "shape", ())) for l in leaves) != plan.shapes:
        raise ValueError(
            'gradient tree shapes changed since DistributedOptimizer'
            '(sharded="full") state was initialized; re-init the optimizer '
            'state for the new parameter tree')
    if op not in (C.ReduceOp.AVERAGE, C.ReduceOp.SUM):
        raise ValueError(f'sharded="full" supports SUM/AVERAGE, not {op!r}')
    nl = len(leaves)

    # Phase 1: every bucket's reduce-scatter goes out before any update
    # runs (same overlap structure as the PR 15 pipeline), stamped with
    # reverse-registration priorities and the "full" digest token.
    rs_handles: List[dict] = []
    for b, idxs in enumerate(plan.buckets):
        live = [i for i in idxs if plan.pers[i] > 0]
        padded = []
        for i in live:
            flat = jnp.ravel(jnp.asarray(leaves[i]))
            if plan.pads[i]:
                flat = jnp.pad(flat, (0, plan.pads[i]))
            padded.append(flat)
        handles = eager.grouped_reducescatter_async(
            padded, name=f"fsdp_rs.b{b}", op=op,
            process_set=process_set,
            priorities=[nl - i for i in live], sharded="full") \
            if padded else []
        rs_handles.append(dict(zip(live, handles)))
    eager._engine().kick()

    # Phase 2: shard-local update against the resident shards; the shards
    # advance here and nothing is gathered — next step's gather_params
    # does that through the prefetch lane.
    new_inner: List = []
    new_shards: List = []
    for b, idxs in enumerate(plan.buckets):
        g_shards = tuple(
            jnp.asarray(eager.to_local(
                eager.synchronize(rs_handles[b][i]))).reshape(-1)
            .astype(plan.dtypes[i]) if plan.pers[i] > 0
            else jnp.zeros((0,), plan.dtypes[i])
            for i in idxs)
        p_shards = state.param_shards[b]
        updates_b, inner_b = optimizer.update(
            g_shards, state.inner_states[b], p_shards)
        new_inner.append(inner_b)
        new_shards.append(tuple(optax.apply_updates(p_shards, updates_b)))
    td = state.treedef if state.treedef is not None else treedef
    return None, FullShardedState(new_inner, plan, process_set,
                                  new_shards, td)


def _make_sharded(optimizer: optax.GradientTransformation,
                  op: C.ReduceOp, axis_name: str,
                  process_set: Optional[ProcessSet],
                  full: bool = False
                  ) -> optax.GradientTransformation:
    """The three sharded modes behind ``DistributedOptimizer(sharded=
    True)`` — and, with ``full=True``, behind ``sharded="full"`` —
    dispatched like ``allreduce_gradients`` dispatches: on whether
    ``axis_name`` is bound (in-graph shard_map), the process is one rank
    of a torovodrun world (eager engine pipeline), or neither
    (single-controller degrade to the plain optimizer).  The state type
    records which mode AND which stage initialized it, so init and
    update can never silently mix modes."""
    from ..parallel import zero

    def _chunk_bytes() -> int:
        from ..common import basics
        st = basics._get_state()
        if st.engine is not None:
            return int(st.engine.pipeline_chunk_bytes)
        return int(st.config.pipeline_chunk_bytes) if st.config else 0

    def init_fn(params):
        if _axis_in_scope(axis_name):
            wrap = zero.full_sharded_optimizer if full \
                else zero.sharded_optimizer
            return wrap(optimizer, axis_name=axis_name,
                        average=op == C.ReduceOp.AVERAGE).init(params)
        from ..ops import eager
        if eager.per_process_mode():
            if full:
                return _full_sharded_eager_init(optimizer, params,
                                                process_set, _chunk_bytes())
            return _sharded_eager_init(optimizer, params, process_set,
                                       _chunk_bytes())
        return optimizer.init(params)      # world of one: nothing to shard

    def update_fn(grads, state, params=None):
        if isinstance(state, zero._FullZeroState):
            return zero.full_sharded_optimizer(
                optimizer, axis_name=axis_name,
                average=op == C.ReduceOp.AVERAGE).update(grads, state,
                                                         params)
        if isinstance(state, zero._ZeroState):
            return zero.sharded_optimizer(
                optimizer, axis_name=axis_name,
                average=op == C.ReduceOp.AVERAGE).update(grads, state,
                                                         params)
        if isinstance(state, FullShardedState):
            return _full_sharded_eager_update(optimizer, grads, state,
                                              op, process_set)
        if isinstance(state, ShardedOptimizerState):
            return _sharded_eager_update(optimizer, grads, state, params,
                                         op, process_set)
        if _axis_in_scope(axis_name) and compat_axis_size(axis_name) > 1:
            # Mixed modes: a plain state initialized OUTSIDE the mesh axis
            # updating INSIDE shard_map.  The plain fallback below would
            # apply raw per-shard gradients with no reduction — silent
            # replica divergence — so fail loudly instead (the replicated
            # path reduces at update time and doesn't have this trap).
            raise RuntimeError(
                "DistributedOptimizer(sharded=True): opt.init(...) ran "
                "outside the mesh axis but opt.update(...) is running "
                "inside shard_map over it.  Initialize inside the same "
                "shard_map context (or build the state with "
                "parallel.zero.init_sharded_state and pass its specs) so "
                "the state is the sharded 1/world layout")
        return optimizer.update(grads, state, params)

    return optax.GradientTransformation(init_fn, update_fn)


def DistributedOptimizer(optimizer: optax.GradientTransformation,
                         named_parameters=None,
                         compression=Compression.none,
                         op: C.ReduceOp = C.ReduceOp.AVERAGE,
                         backward_passes_per_step: int = 1,
                         axis_name: str = C.DEFAULT_AXIS,
                         process_set: Optional[ProcessSet] = None,
                         check=False,
                         sharded=None,
                         ) -> optax.GradientTransformation:
    """Wrap an optax optimizer with cross-rank gradient averaging.

    Usage (inside a shard_map/pjit train step over the ``hvd`` axis):

        opt = hvd.DistributedOptimizer(optax.adam(1e-3))
        updates, opt_state = opt.update(grads, opt_state, params)

    ``backward_passes_per_step > 1`` reproduces the reference's gradient
    aggregation (``horovod/tensorflow/gradient_aggregation.py``): gradients
    accumulate locally and the (single) allreduce happens every k-th step.
    ``named_parameters`` is accepted for API parity and unused (pytrees are
    self-describing).

    ``check=True`` lints the calling script for deadlock-prone collective
    patterns at wrap time (``check="strict"`` raises on errors) — see
    ``horovod_tpu.analysis`` and docs/analysis.md.

    ``sharded=True`` (ISSUE 15, the ZeRO decomposition — Rajbhandari et
    al.): optimizer state lives 1/world per rank, gradients ride a
    **reduce-scatter** (each rank receives only its shard — half the wire
    bytes of an allreduce of the same payload), the inner update runs on
    the shard, and the updated deltas **allgather** back.  Parameters
    after K steps are bitwise-identical to ``sharded=False`` for
    elementwise optimizers (sgd/adam/...; reduction order is pinned the
    same way fused allreduce pins it — see docs/performance.md "Sharded
    optimizer (ZeRO)").  In-graph (inside shard_map over ``axis_name``)
    this wraps ``parallel.zero.sharded_optimizer``; eagerly
    (torovodrun-launched) it pipelines per-bucket scatter → shard update
    → gather through the collective engine, bucket size set by
    ``HOROVOD_PIPELINE_CHUNK``.  Single-controller SPMD outside any mesh
    axis degrades to the plain optimizer (a world of one has nothing to
    shard), like ``allreduce_gradients`` degrades to the identity.
    Default ``sharded=None`` reads ``HOROVOD_SHARDED_OPTIMIZER``.

    ``sharded="full"`` (ISSUE 18, ZeRO-3 / FSDP): parameters themselves
    live 1/world per rank.  Gradients **reduce-scatter straight into the
    owning shard** (no replicated gradient ever exists), the inner update
    runs shard-local, and ``update`` returns ``(None, state)`` — the
    training loop rematerializes full parameters each step with
    ``state.gather_params()``, whose per-bucket allgathers ride the
    engine's PREFETCH lane ``HOROVOD_PREFETCH_DEPTH`` buckets ahead of
    consumption.  Parameters after K steps are bitwise-identical to the
    replicated path; wire bytes per step (RS + AG) equal ``sharded=True``;
    resident parameter+gradient+optimizer bytes drop to ≈ 1/world.
    In-graph this wraps ``parallel.zero.full_sharded_optimizer`` (state
    carries the resident shards; see also ``zero.gather_full_params`` and
    ``zero.init_full_sharded_state``).  Default ``sharded=None`` reads
    ``HOROVOD_SHARDED_PARAMS`` first (→ ``"full"``), then
    ``HOROVOD_SHARDED_OPTIMIZER`` (→ ``True``).
    """
    del named_parameters
    if check:
        from ..analysis.hooks import run_check_hook
        run_check_hook(check)
    if process_set is not None:
        axis_name = process_set.axis_name
    k = backward_passes_per_step
    if sharded is None:
        from ..common import basics
        cfg = basics._get_state().config
        if cfg is not None and getattr(cfg, "sharded_params", False):
            sharded = "full"
        else:
            sharded = bool(cfg is not None
                           and getattr(cfg, "sharded_optimizer", False))
    if sharded not in (False, True, "full"):
        raise ValueError(
            f"sharded= must be False, True, or 'full'; got {sharded!r}")
    if sharded:
        label = 'sharded="full"' if sharded == "full" else "sharded=True"
        if k != 1:
            raise NotImplementedError(
                f"DistributedOptimizer({label}) does not compose with "
                "backward_passes_per_step > 1 yet: accumulate locally and "
                "call update every k-th step instead")
        wire = getattr(compression, "wire_mode", None)
        if wire is not None:
            raise NotImplementedError(
                f"DistributedOptimizer({label}) does not support wire "
                "compression yet: the gather leg carries parameter deltas "
                "whose precision is the training result, not a gradient")
        return _make_sharded(optimizer, op, axis_name, process_set,
                             full=sharded == "full")

    def init_fn(params):
        inner = optimizer.init(params)
        if k == 1:
            return _DistOptState(inner, (), jnp.zeros((), jnp.int32))
        acc = jax.tree_util.tree_map(jnp.zeros_like, params)
        return _DistOptState(inner, acc, jnp.zeros((), jnp.int32))

    def _reduce(grads):
        return allreduce_gradients(grads, op=op, axis_name=axis_name,
                                   compression=compression,
                                   process_set=process_set)

    def update_fn(grads, state: _DistOptState, params=None):
        if k == 1:
            updates, inner = optimizer.update(_reduce(grads), state.inner_state,
                                              params)
            return updates, _DistOptState(inner, (), state.counter + 1)

        acc = jax.tree_util.tree_map(lambda a, g: a + g, state.acc, grads)
        counter = state.counter + 1
        apply_now = (counter % k) == 0

        def _do_apply_concrete(acc_, inner_):
            mean_acc = jax.tree_util.tree_map(lambda a: a / k, acc_)
            updates, new_inner = optimizer.update(_reduce(mean_acc), inner_,
                                                  params)
            zeroed = jax.tree_util.tree_map(jnp.zeros_like, acc_)
            return updates, new_inner, zeroed

        # Eager per-process calls must NOT go through lax.cond: it traces
        # both branches, which would trace the engine allreduce.  With a
        # concrete counter a plain Python branch is exact.
        if not isinstance(apply_now, jax.core.Tracer):
            if bool(apply_now):
                updates, inner, acc = _do_apply_concrete(acc, state.inner_state)
            else:
                updates = jax.tree_util.tree_map(jnp.zeros_like, acc)
                inner = state.inner_state
            return updates, _DistOptState(inner, acc, counter)

        def do_apply(operand):
            acc_, inner_ = operand
            mean_acc = jax.tree_util.tree_map(lambda a: a / k, acc_)
            updates, new_inner = optimizer.update(_reduce(mean_acc), inner_,
                                                  params)
            zeroed = jax.tree_util.tree_map(jnp.zeros_like, acc_)
            return updates, new_inner, zeroed

        def skip(operand):
            acc_, inner_ = operand
            updates = jax.tree_util.tree_map(jnp.zeros_like, acc_)
            return updates, inner_, acc_

        updates, inner, acc = lax.cond(apply_now, do_apply, skip,
                                       (acc, state.inner_state))
        return updates, _DistOptState(inner, acc, counter)

    return optax.GradientTransformation(init_fn, update_fn)


def DistributedGradientTape(grad_fn: Callable,
                            compression=Compression.none,
                            op: C.ReduceOp = C.ReduceOp.AVERAGE,
                            axis_name: str = C.DEFAULT_AXIS,
                            process_set: Optional[ProcessSet] = None) -> Callable:
    """Wrap a gradient function so its output gradients are allreduced.

    The JAX rendering of ``hvd.DistributedGradientTape`` (reference
    ``horovod/tensorflow/__init__.py`` §3.5): pass ``jax.grad(loss_fn)`` or
    ``jax.value_and_grad(loss_fn)``; the wrapper averages whatever gradient
    pytree comes back.  Works with ``value_and_grad`` by reducing only the
    gradient half of the result.
    """
    def wrapped(*args, **kwargs):
        out = grad_fn(*args, **kwargs)
        if isinstance(out, tuple) and len(out) == 2:
            value, grads = out
            return value, allreduce_gradients(
                grads, op=op, axis_name=axis_name, compression=compression,
                process_set=process_set)
        return allreduce_gradients(out, op=op, axis_name=axis_name,
                                   compression=compression,
                                   process_set=process_set)
    return wrapped


def broadcast_parameters(params, root_rank: int = 0,
                         process_set: Optional[ProcessSet] = None):
    """Synchronize a parameter pytree from ``root_rank`` to all ranks.

    Reference: ``horovod/torch/functions.py broadcast_parameters``.  In
    single-controller SPMD there is exactly one copy of the params (a global
    ``jax.Array``), so all "ranks" are synchronized by construction and this
    is the identity.  In multi-process mode each process holds its own copy
    and the byte-level broadcast runs through the coordinator.
    """
    if jax.process_count() == 1:
        return params
    from ..ops import eager
    out = eager.broadcast_pytree(params, root_rank=root_rank,
                                 process_set=process_set)
    return jax.tree_util.tree_map(jnp.asarray, out)


def broadcast_optimizer_state(opt_state, root_rank: int = 0,
                              process_set: Optional[ProcessSet] = None):
    """Reference: ``horovod/torch/functions.py broadcast_optimizer_state``."""
    return broadcast_parameters(opt_state, root_rank=root_rank,
                                process_set=process_set)
