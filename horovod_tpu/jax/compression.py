"""Gradient compression for collective transfers.

Parity with the reference's ``horovod/torch/compression.py`` /
``horovod/tensorflow/compression.py`` (SURVEY.md §2b P2/P4): a ``Compression``
namespace with ``none`` and ``fp16`` compressors, each exposing
``compress(tensor) -> (tensor, ctx)`` and ``decompress(tensor, ctx)``.

TPU-first difference: the native low-precision type is **bfloat16** (MXU- and
ICI-friendly, no loss-scale needed), so ``fp16`` maps to bf16 by default with
an explicit ``float16`` variant for byte-parity experiments.  Inside jit, the
cast fuses into the collective's producer — the reference needs a dedicated
CUDA scale/cast kernel (N18) for this; XLA gives it for free.
"""

from __future__ import annotations

import jax.numpy as jnp


class Compressor:
    """Interface matching the reference's Compressor base class.

    ``wire_mode`` (``"bf16"``/``"fp16"``/``None``): set on cast-style
    compressors so the optimizer bindings route them through the engine's
    FUSED wire compression (cast-down/cast-up inside the jitted collective
    program) instead of calling compress/decompress as separate passes.
    Custom compressors leave it ``None`` and keep the explicit hooks."""

    wire_mode = None

    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class BF16Compressor(Compressor):
    """Cast floating tensors to bfloat16 for transfer, restore dtype after."""

    wire_mode = "bf16"

    @staticmethod
    def compress(tensor):
        if jnp.issubdtype(tensor.dtype, jnp.floating):
            return tensor.astype(jnp.bfloat16), tensor.dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor.astype(ctx) if ctx is not None else tensor


class FP16Compressor(Compressor):
    """Strict float16 transfer (byte-parity with the reference's fp16)."""

    wire_mode = "fp16"

    @staticmethod
    def compress(tensor):
        if jnp.issubdtype(tensor.dtype, jnp.floating):
            return tensor.astype(jnp.float16), tensor.dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor.astype(ctx) if ctx is not None else tensor


class Compression:
    """Namespace mirroring ``hvd.Compression``."""
    none = NoneCompressor
    fp16 = BF16Compressor       # TPU-native: bf16 wire format
    fp16_strict = FP16Compressor
    bf16 = BF16Compressor
