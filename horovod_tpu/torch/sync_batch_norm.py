"""Cross-rank synchronized batch normalization for the torch binding.

Parity: reference ``horovod/torch/sync_batch_norm.py`` — a drop-in
``_BatchNorm`` subclass whose training-mode statistics are computed over the
GLOBAL batch (all ranks), via allreduce of per-rank sums in forward and of
gradient sums in backward.
"""

from __future__ import annotations

import torch
import torch.nn.functional as F
from torch.autograd.function import Function
from torch.nn.modules.batchnorm import _BatchNorm

import itertools

from . import mpi_ops
from ..common import basics

# Collective names must be identical across ranks for negotiation to match;
# every rank executes the same module sequence, so call-order counters align
# (and reset together with the runtime, for elastic re-inits).
_fwd_counter = itertools.count(0)
_bwd_counter = itertools.count(0)


def _reset_counters():
    global _fwd_counter, _bwd_counter
    _fwd_counter = itertools.count(0)
    _bwd_counter = itertools.count(0)


from ..ops.eager import register_name_counter_reset  # noqa: E402
register_name_counter_reset(_reset_counters)


class SyncBatchNorm(_BatchNorm):
    """BatchNorm with statistics synchronized across all ranks.

    Matches the reference's semantics: in eval mode (or world size 1) it is
    exactly ``torch.nn.BatchNorm*``; in training mode mean/var come from the
    global batch.
    """

    def __init__(self, num_features, eps=1e-5, momentum=0.1, affine=True,
                 track_running_stats=True, process_set=None):
        super().__init__(num_features, eps, momentum, affine,
                         track_running_stats)
        self.process_set = process_set

    def _run_bn(self, input):
        return F.batch_norm(
            input, self.running_mean, self.running_var, self.weight,
            self.bias, self.training or not self.track_running_stats,
            self.momentum, self.eps)

    def forward(self, input):
        if input.dim() < 2:
            raise ValueError(
                f"expected at least 2D input (got {input.dim()}D)")
        if not (self.training and
                (basics.is_initialized() and basics.size() > 1)):
            return self._run_bn(input)
        if self.num_batches_tracked is not None:
            self.num_batches_tracked = self.num_batches_tracked + 1
        # momentum=None is _BatchNorm's cumulative-moving-average mode.
        momentum = self.momentum
        if momentum is None:
            momentum = (1.0 / float(self.num_batches_tracked)
                        if self.num_batches_tracked is not None else 0.1)
        return _SyncBatchNormFn.apply(
            input, self.weight, self.bias, self.running_mean,
            self.running_var, self.eps, momentum, self.process_set)


class _SyncBatchNormFn(Function):
    @staticmethod
    def forward(ctx, input, weight, bias, running_mean, running_var, eps,
                momentum, process_set):
        c = input.shape[1]
        reduce_dims = [0] + list(range(2, input.dim()))
        local_count = input.numel() // c
        # One fused allreduce for [sum, sqsum, count] — the reference issues
        # separate mean/var allgathers; summing is both cheaper and exact
        # for heterogeneous per-rank batch sizes.
        stats = torch.empty(2 * c + 1, dtype=torch.float32)
        stats[:c] = input.sum(dim=reduce_dims).float()
        stats[c:2 * c] = (input * input).sum(dim=reduce_dims).float()
        stats[2 * c] = float(local_count)
        g = mpi_ops.allreduce(stats, op=mpi_ops.Sum,
                              name=f"sync_bn.fwd.{next(_fwd_counter)}",
                              process_set=process_set)
        total = g[2 * c].clamp(min=1.0)
        mean = g[:c] / total
        var = g[c:2 * c] / total - mean * mean
        var = var.clamp(min=0.0)

        if running_mean is not None:
            unbiased = var * (total / (total - 1.0).clamp(min=1.0))
            running_mean.mul_(1 - momentum).add_(mean.to(running_mean.dtype),
                                                 alpha=momentum)
            running_var.mul_(1 - momentum).add_(unbiased.to(running_var.dtype),
                                                alpha=momentum)

        shape = [1, c] + [1] * (input.dim() - 2)
        invstd = torch.rsqrt(var + eps)
        xhat = (input.float() - mean.reshape(shape)) * invstd.reshape(shape)
        out = xhat
        if weight is not None:
            out = out * weight.float().reshape(shape)
        if bias is not None:
            out = out + bias.float().reshape(shape)
        ctx.save_for_backward(xhat, weight, invstd, total)
        ctx.process_set = process_set
        ctx.has_bias = bias is not None
        return out.to(input.dtype)

    @staticmethod
    def backward(ctx, grad_output):
        xhat, weight, invstd, total = ctx.saved_tensors
        c = xhat.shape[1]
        reduce_dims = [0] + list(range(2, xhat.dim()))
        shape = [1, c] + [1] * (xhat.dim() - 2)

        go = grad_output.float()
        # Local per-channel grad sums, then one fused global SUM.
        sums = torch.empty(2 * c, dtype=torch.float32)
        sums[:c] = go.sum(dim=reduce_dims)
        sums[c:] = (go * xhat).sum(dim=reduce_dims)
        g = mpi_ops.allreduce(sums, op=mpi_ops.Sum,
                              name=f"sync_bn.bwd.{next(_bwd_counter)}",
                              process_set=ctx.process_set)
        sum_dy = g[:c]
        sum_dy_xhat = g[c:]

        grad_weight = (go * xhat).sum(dim=reduce_dims) \
            if weight is not None else None
        grad_bias = go.sum(dim=reduce_dims) if ctx.has_bias else None

        w = weight.float().reshape(shape) if weight is not None else 1.0
        gx = (w * invstd.reshape(shape)) * (
            go - (sum_dy / total).reshape(shape)
            - xhat * (sum_dy_xhat / total).reshape(shape))
        return (gx.to(grad_output.dtype),
                grad_weight.to(weight.dtype) if weight is not None else None,
                grad_bias, None, None, None, None, None)
