"""``import horovod_tpu.torch as hvd`` — the PyTorch binding.

Mirrors the reference's ``horovod.torch`` module surface (SURVEY.md §2b P2):
runtime control (init/rank/size/...), collectives over torch tensors,
``DistributedOptimizer``, parameter/optimizer-state broadcast, compression,
``SyncBatchNorm`` and the elastic submodule.  The data plane underneath is
the same TPU coordinator + XLA collectives the JAX binding uses.
"""

from ..common.basics import (  # noqa: F401
    init, shutdown, is_initialized,
    rank, size, local_rank, local_size, cross_rank, cross_size,
    mesh, is_homogeneous,
    add_process_set, remove_process_set, process_set_included,
    xla_built, nccl_built, mpi_enabled, gloo_enabled, mpi_threads_supported,
    cuda_built, rocm_built, tpu_available,
    start_timeline, stop_timeline, start_profile, stop_profile,
    profile_step,
    NotInitializedError,
)
from ..common.process_sets import ProcessSet, global_process_set  # noqa: F401
from .mpi_ops import (  # noqa: F401
    ReduceOp, Average, Sum, Adasum, Min, Max, Product,
    allreduce, allreduce_, allreduce_async, allreduce_async_,
    grouped_allreduce, grouped_allreduce_, grouped_allreduce_async,
    grouped_allreduce_async_,
    allgather, allgather_async,
    broadcast, broadcast_, broadcast_async, broadcast_async_,
    broadcast_object, allgather_object,
    alltoall, alltoall_async,
    reducescatter, reducescatter_async,
    synchronize, poll, barrier, join,
)
from .compression import Compression  # noqa: F401
from .functions import (  # noqa: F401
    broadcast_parameters, broadcast_optimizer_state,
)
from .optimizer import DistributedOptimizer  # noqa: F401
from .sync_batch_norm import SyncBatchNorm  # noqa: F401
from . import elastic  # noqa: F401
