"""Elastic API for the torch binding (reference: ``horovod.torch.elastic``)."""

from ...elastic.state import (  # noqa: F401
    HorovodInternalError, HostsUpdatedInterrupt, ObjectState, State, run,
)
from .sampler import ElasticSampler  # noqa: F401
from .state import TorchState  # noqa: F401
