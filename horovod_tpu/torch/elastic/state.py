"""Elastic state for the torch binding.

Parity: reference ``horovod/torch/elastic/state.py`` — ``TorchState``
captures model/optimizer (and arbitrary scalar) state with in-memory
``commit``/``restore`` and rank-0 ``sync`` (SURVEY.md §3.4).
"""

from __future__ import annotations

import copy
from typing import Any, Dict

import torch

from ...elastic.state import ObjectState
from .. import functions, mpi_ops


class _HandlerBase:
    def __init__(self, value):
        self.value = value

    def save(self):
        raise NotImplementedError

    def restore(self):
        raise NotImplementedError

    def sync(self):
        raise NotImplementedError


class TorchModelHandler(_HandlerBase):
    def __init__(self, model: torch.nn.Module):
        super().__init__(model)
        self.save()

    def save(self):
        self._saved = copy.deepcopy(self.value.state_dict())

    def restore(self):
        self.value.load_state_dict(self._saved)

    def sync(self):
        functions.broadcast_parameters(self.value.state_dict(), root_rank=0)


class TorchOptimizerHandler(_HandlerBase):
    def __init__(self, optimizer: torch.optim.Optimizer):
        super().__init__(optimizer)
        self.save()

    def save(self):
        self._saved = copy.deepcopy(self.value.state_dict())

    def restore(self):
        self.value.load_state_dict(self._saved)

    def sync(self):
        functions.broadcast_optimizer_state(self.value, root_rank=0)


class TorchState(ObjectState):
    """Elastic training state holding torch models/optimizers.

    Usage mirrors the reference::

        state = hvd.elastic.TorchState(model=model, optimizer=opt, epoch=0)
        @hvd.elastic.run
        def train(state): ...
    """

    def __init__(self, model=None, optimizer=None, **kwargs):
        self._handlers: Dict[str, _HandlerBase] = {}
        scalars: Dict[str, Any] = {}
        if model is not None:
            self._handlers["model"] = TorchModelHandler(model)
        if optimizer is not None:
            self._handlers["optimizer"] = TorchOptimizerHandler(optimizer)
        for k, v in kwargs.items():
            if isinstance(v, torch.nn.Module):
                self._handlers[k] = TorchModelHandler(v)
            elif isinstance(v, torch.optim.Optimizer):
                self._handlers[k] = TorchOptimizerHandler(v)
            else:
                scalars[k] = v
        super().__init__(**scalars)

    def __getattr__(self, name):
        handlers = self.__dict__.get("_handlers", {})
        if name in handlers:
            return handlers[name].value
        raise AttributeError(name)

    def save(self):
        for h in self._handlers.values():
            h.save()
        super().save()

    def restore(self):
        for h in self._handlers.values():
            h.restore()
        super().restore()

    def sync(self):
        for h in self._handlers.values():
            h.sync()
        super().sync()
