"""Shard-aware elastic sampler.

Parity: reference ``horovod/torch/elastic/sampler.py`` ``ElasticSampler`` —
shards the dataset by (rank, size), tracks processed indices so a rank
re-joining after an elastic reset resumes mid-epoch without repeating data.
"""

from __future__ import annotations

import math
import random
from typing import Iterator, List

import torch.utils.data

from ...common import basics


class ElasticSampler(torch.utils.data.Sampler):
    def __init__(self, dataset, shuffle: bool = True, seed: int = 0):
        self.dataset = dataset
        self.shuffle = shuffle
        self.seed = seed
        self.epoch = 0
        self.processed_indices: set = set()

        self.num_replicas = 0
        self.rank = 0
        self.remaining_indices: List[int] = []
        self.num_samples = 0
        self.total_size = 0
        self.reset()

    def set_epoch(self, epoch: int):
        """New epoch: clear processed set and reshuffle."""
        self.epoch = epoch
        self.processed_indices = set()
        self.reset()

    def record_batch(self, batch_idx: int, batch_size: int):
        """Record consumption of one batch of this rank's shard.

        Offsets index ``remaining_indices`` — the list ``__iter__`` actually
        serves — so recording stays correct after a mid-epoch reset has
        filtered out already-processed entries.
        """
        start = self.rank + batch_idx * batch_size * self.num_replicas
        processed = []
        for i in range(batch_size):
            offset = start + i * self.num_replicas
            if offset < self.total_size:
                processed.append(self.remaining_indices[offset])
        self.processed_indices.update(processed)

    def record_indices(self, indices):
        self.processed_indices.update(indices)

    def reset(self):
        """Re-shard after world-size change (called by state.on_reset)."""
        self.num_replicas = basics.size() if basics.is_initialized() else 1
        self.rank = basics.rank() if basics.is_initialized() else 0

        indices = list(range(len(self.dataset)))
        if self.shuffle:
            random.Random(self.seed + self.epoch).shuffle(indices)
        self.indices = indices

        remaining = [i for i in self.indices
                     if i not in self.processed_indices]
        self.num_samples = int(
            math.ceil(len(remaining) / max(self.num_replicas, 1)))
        self.total_size = self.num_samples * self.num_replicas
        # Pad so every rank sees the same number of samples.
        remaining += remaining[:self.total_size - len(remaining)]
        self.remaining_indices = remaining

    def state_dict(self):
        return {"epoch": self.epoch,
                "processed_indices": sorted(self.processed_indices)}

    def load_state_dict(self, state):
        self.epoch = state["epoch"]
        self.processed_indices = set(state["processed_indices"])
        self.reset()

    def __iter__(self) -> Iterator[int]:
        return iter(self.remaining_indices[self.rank:self.total_size:
                                           self.num_replicas])

    def __len__(self) -> int:
        return self.num_samples
