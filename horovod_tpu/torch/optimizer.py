"""``hvd.DistributedOptimizer`` for PyTorch.

Parity: reference ``horovod/torch/optimizer.py`` ``_DistributedOptimizer`` —
per-parameter gradient hooks fire async allreduces during ``backward()``;
``step()`` calls ``synchronize()`` to wait for and apply the averaged
gradients, then runs the wrapped optimizer.  Supports
``backward_passes_per_step`` local aggregation, compression, ``Sum`` /
``Average`` / ``Adasum`` ops, pre/post-scale factors, process sets, and
``skip_synchronize()``.

TPU-native notes: the async enqueue lands in the same coordinator the JAX
path uses (fusion/negotiation/caching apply); the wire dtype can be dropped
to bf16 via ``Compression.bf16`` which XLA handles natively on the MXU.
"""

from __future__ import annotations

from contextlib import contextmanager
from typing import Optional

import torch

from . import mpi_ops
from .compression import Compression
from ..common import basics
from ..common.process_sets import ProcessSet


class _DistributedOptimizer(torch.optim.Optimizer):
    def __init__(self, params, named_parameters=None,
                 compression=Compression.none,
                 backward_passes_per_step=1,
                 op=mpi_ops.Average,
                 gradient_predivide_factor=1.0,
                 process_set: Optional[ProcessSet] = None):
        super(self.__class__, self).__init__(params)
        self._compression = compression
        self.op = op
        self.gradient_predivide_factor = gradient_predivide_factor
        self.process_set = process_set
        self.backward_passes_per_step = backward_passes_per_step

        if named_parameters is not None:
            named_parameters = list(named_parameters)
        else:
            named_parameters = [(f"allreduce.noname.{i}.{j}", v)
                                for i, group in enumerate(self.param_groups)
                                for j, v in enumerate(group["params"])]
        if len(named_parameters) > 0 and not isinstance(
                named_parameters[0][1], torch.Tensor):
            raise ValueError("named_parameters should be a sequence of "
                             "(name, torch.Tensor) pairs")
        all_params = {p for group in self.param_groups
                      for p in group["params"]}
        named = {p for _, p in named_parameters}
        unnamed = all_params - named
        if unnamed:
            raise ValueError(
                f"named_parameters was specified but {len(unnamed)} "
                f"optimizer parameters were not named")
        dups = _find_duplicates([k for k, _ in named_parameters])
        if dups:
            raise ValueError(f"Parameter names are not unique: {dups}")

        self._parameter_names = {v: k for k, v in named_parameters}
        # Reverse-registration drain priority: the first-registered
        # parameter (first layer touched by the next forward pass) gets the
        # highest priority, so its gradient — produced LAST by backprop —
        # still leads the next coordinator cycle (ByteScheduler-style
        # scheduling).  Registration order matches across ranks, so the
        # stamps agree.
        self._priorities = {p: len(named_parameters) - i
                            for i, (_, p) in enumerate(named_parameters)}
        self._handles = {}
        self._grad_accs = []
        self._requires_update = set()
        self._synchronized = False
        self._should_synchronize = True
        self._allreduce_delay = {}

        if basics.size() > 1:
            self._register_hooks()

    # ----------------------------------------------------------- hooks
    def _register_hooks(self):
        for param_group in self.param_groups:
            for p in param_group["params"]:
                if p.requires_grad:
                    self._requires_update.add(p)
                    self._allreduce_delay[p] = self.backward_passes_per_step
                    if hasattr(p, "register_post_accumulate_grad_hook"):
                        p.register_post_accumulate_grad_hook(
                            self._make_post_hook(p))
                    else:  # pragma: no cover - old torch
                        p.grad = p.data.new(p.size()).zero_()
                        p_tmp = p.expand_as(p)
                        grad_acc = p_tmp.grad_fn.next_functions[0][0]
                        grad_acc.register_hook(self._make_hook(p))
                        self._grad_accs.append(grad_acc)

    def _make_post_hook(self, p):
        def hook(param):
            self._hook_body(p)
        return hook

    def _make_hook(self, p):  # pragma: no cover - old torch
        def hook(*ignore):
            self._hook_body(p)
        return hook

    def _hook_body(self, p):
        if p in self._handles and self._handles[p][0] is not None:
            if self._allreduce_delay[p] <= 0:
                raise AssertionError(
                    "Gradients were computed more than "
                    "backward_passes_per_step times before call to step(). "
                    "Increase backward_passes_per_step to accumulate "
                    "gradients locally.")
        assert not p.grad.requires_grad
        assert self._allreduce_delay[p] > 0
        handle, ctx = None, None
        self._allreduce_delay[p] -= 1
        if self._allreduce_delay[p] == 0:
            handle, ctx = self._allreduce_grad_async(p)
        self._handles[p] = (handle, ctx)

    def _allreduce_grad_async(self, p):
        name = self._parameter_names.get(p)
        tensor = p.grad
        # Average semantics with local aggregation: divide by the number of
        # locally accumulated passes so the wire value is the per-pass mean.
        prescale = None
        postscale = None
        if self.op == mpi_ops.Average:
            if self.gradient_predivide_factor != 1.0:
                prescale = 1.0 / self.gradient_predivide_factor
                postscale = self.gradient_predivide_factor / basics.size()
                wire_op = mpi_ops.Sum
            else:
                wire_op = mpi_ops.Average
            # Average semantics only: locally accumulated N passes are
            # divided back to the per-pass mean; Sum/Adasum keep the raw sum.
            if self.backward_passes_per_step > 1:
                prescale = (prescale or 1.0) / self.backward_passes_per_step
        else:
            wire_op = self.op
        # Cast-style compressors (wire_mode attr) ride the fused wire-
        # compression path: the cast pair lives inside the jitted
        # collective program, the result comes back in the gradient's
        # dtype (ctx None → decompress is the identity).  Custom
        # compressors keep the explicit compress/decompress hooks.
        prio = self._priorities.get(p, 0)
        wire = getattr(self._compression, "wire_mode", None)
        if wire is not None:
            handle = mpi_ops.allreduce_async(
                tensor, name=f"allreduce.{name}", op=wire_op,
                prescale_factor=prescale, postscale_factor=postscale,
                process_set=self.process_set, compression=wire,
                priority=prio)
            return handle, None
        tensor_compressed, ctx = self._compression.compress(tensor)
        handle = mpi_ops.allreduce_async(
            tensor_compressed, name=f"allreduce.{name}", op=wire_op,
            prescale_factor=prescale, postscale_factor=postscale,
            process_set=self.process_set, priority=prio)
        return handle, ctx

    # ----------------------------------------------------------- step
    def synchronize(self):
        """Wait for all outstanding gradient allreduces and write the
        averaged gradients back (reference: ``synchronize()``)."""
        if basics.size() <= 1:
            self._synchronized = True
            return
        # Params whose hook never fired this step (e.g. unused branch):
        # submit now so all ranks stay consistent.
        for p in self._requires_update:
            if p not in self._handles:
                if p.grad is None:
                    p.grad = torch.zeros_like(p)
                handle, ctx = self._allreduce_grad_async(p)
                self._handles[p] = (handle, ctx)
        for p, (handle, ctx) in list(self._handles.items()):
            if handle is None:
                handle, ctx = self._allreduce_grad_async(p)
                self._handles[p] = (handle, ctx)
        for p, (handle, ctx) in self._handles.items():
            output = mpi_ops.synchronize(handle)
            self._allreduce_delay[p] = self.backward_passes_per_step
            p.grad.data.copy_(
                self._compression.decompress(output, ctx).reshape(p.grad.shape))
        self._handles.clear()
        self._synchronized = True

    @contextmanager
    def skip_synchronize(self):
        """With this context, ``step()`` will not re-synchronize — used when
        the user called ``synchronize()`` manually (e.g. before gradient
        clipping), matching the reference's API."""
        self._should_synchronize = False
        try:
            yield
        finally:
            self._should_synchronize = True

    def step(self, closure=None):
        if self._should_synchronize:
            if self._synchronized:
                import warnings
                warnings.warn(
                    "optimizer.step() called without a prior backward pass "
                    "re-synchronizing; call optimizer.skip_synchronize() "
                    "around step() if you synchronized manually")
            self.synchronize()
        self._synchronized = False
        return super(self.__class__, self).step(closure)

    def zero_grad(self, *args, **kwargs):
        if self._handles:
            raise AssertionError(
                "optimizer.zero_grad() was called after loss.backward() but "
                "before optimizer.step() or optimizer.synchronize(). This is "
                "prohibited as it can cause a race condition.")
        return super(self.__class__, self).zero_grad(*args, **kwargs)


def _find_duplicates(lst):
    seen, dups = set(), set()
    for x in lst:
        if x in seen:
            dups.add(x)
        seen.add(x)
    return dups


def DistributedOptimizer(optimizer, named_parameters=None,
                         compression=Compression.none,
                         backward_passes_per_step=1,
                         op=mpi_ops.Average,
                         gradient_predivide_factor=1.0,
                         process_set: Optional[ProcessSet] = None,
                         check=False):
    """Wrap a torch optimizer so ``step()`` applies globally averaged
    gradients (reference: ``hvd.DistributedOptimizer``).

    Built dynamically as a subclass of the wrapped optimizer's class (the
    reference's pattern), so ``isinstance(opt, torch.optim.SGD)`` holds.

    ``check=True`` lints the calling script for deadlock-prone collective
    patterns at wrap time (``check="strict"`` raises on errors) — see
    ``horovod_tpu.analysis`` and docs/analysis.md.
    """
    if check:
        from ..analysis.hooks import run_check_hook
        run_check_hook(check)
    if gradient_predivide_factor != 1.0 and op != mpi_ops.Average:
        raise ValueError(
            "gradient_predivide_factor not supported with op != Average")
    if op == mpi_ops.Adasum and gradient_predivide_factor != 1.0:
        raise ValueError(
            "gradient_predivide_factor not supported with Adasum")
    cls = type(optimizer.__class__.__name__, (optimizer.__class__,),
               dict(_DistributedOptimizer.__dict__))
    return cls(optimizer.param_groups, named_parameters, compression,
               backward_passes_per_step, op, gradient_predivide_factor,
               process_set)
