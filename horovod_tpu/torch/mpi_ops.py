"""PyTorch op surface: collectives over ``torch.Tensor``.

Parity target: the reference's ``horovod/torch/mpi_ops.py`` +
``mpi_ops_v2.cc`` (SURVEY.md §2a N26, §2b P2) — blocking and ``_async``
variants of allreduce / grouped_allreduce / allgather / broadcast / alltoall
/ reducescatter (plus in-place ``*_`` forms), integer handles with
``synchronize``/``poll``, ``join`` and ``barrier``.

TPU-native design: there is no per-framework C++ shim registering async ops
with an executor — torch tensors are bridged to host memory and submitted to
the same background coordinator (``ops/engine.py``) the JAX path uses, so
negotiation, fusion, response caching, timeline and stall inspection all
apply identically.  The data plane stays XLA collectives.

Rank semantics match the reference: one process = one rank's contribution.
Under ``torovodrun`` each process submits its local tensor.  In
single-process SPMD mode (one controller owning all ``hvd.size()`` devices)
the process submits on behalf of every rank, i.e. each rank contributes the
same tensor — AVERAGE is then the identity and SUM multiplies by ``size()``.
"""

from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Sequence

import numpy as np
import torch

from ..common import basics
from ..common.process_sets import ProcessSet
from ..ops import collectives as C
from ..ops import eager

ReduceOp = C.ReduceOp
Average = C.ReduceOp.AVERAGE
Sum = C.ReduceOp.SUM
Min = C.ReduceOp.MIN
Max = C.ReduceOp.MAX
Product = C.ReduceOp.PRODUCT
Adasum = C.Adasum

_handle_counter = itertools.count(1)
_handles: Dict[int, "_PendingOp"] = {}


class _PendingOp:
    """Maps an engine handle back to torch-land (dtype/device, in-place dst)."""

    def __init__(self, inner_handle: int, dtype: torch.dtype,
                 device: torch.device, out: Optional[torch.Tensor] = None,
                 postprocess=None):
        self.inner = inner_handle
        self.dtype = dtype
        self.device = device
        self.out = out
        self.postprocess = postprocess


def _to_numpy(t: torch.Tensor) -> np.ndarray:
    """torch -> numpy preserving dtype (bf16 via ml_dtypes bit view)."""
    t = t.detach()
    if t.device.type != "cpu":
        t = t.cpu()
    if t.dtype == torch.bfloat16:
        import ml_dtypes
        return t.contiguous().view(torch.int16).numpy().view(ml_dtypes.bfloat16)
    return t.contiguous().numpy()


def _to_jax(t: torch.Tensor):
    """torch -> jax via dlpack (zero host copy; reference N26's adapter
    wrapped at::Tensor without copies — this is the XLA-side analogue).

    Falls back to the numpy bit-view path for dtypes/layouts dlpack can't
    express.  The returned jax.Array shares (or minimally copies) the torch
    buffer; downstream the engine assembles/donates fresh device buffers, so
    the torch tensor is never invalidated.
    """
    import jax.numpy as jnp
    try:
        return jnp.from_dlpack(t.detach().contiguous())
    except Exception:
        return _to_numpy(t)


def _from_numpy(a: np.ndarray, dtype: torch.dtype,
                device: torch.device) -> torch.Tensor:
    import ml_dtypes
    if a.dtype == ml_dtypes.bfloat16:
        t = torch.from_numpy(a.view(np.int16).copy()).view(torch.bfloat16)
    else:
        a = np.ascontiguousarray(a)
        if not a.flags.writeable:
            a = a.copy()
        t = torch.from_numpy(a)
    if t.dtype != dtype:
        t = t.to(dtype)
    if device.type != "cpu":
        t = t.to(device)
    return t


def _set_size(process_set: Optional[ProcessSet]) -> int:
    return process_set.size() if process_set is not None else basics.size()


def _submit(t: torch.Tensor, process_set: Optional[ProcessSet] = None):
    """This process's contribution in the eager layer's expected form.

    Multi-process: the local tensor as-is (eager._as_stacked assembles the
    global array from per-process shards).  Single-process SPMD: replicate —
    the controller submits the same tensor for every rank it owns.
    """
    if eager.per_process_mode():
        # The real multi-chip path: keep the tensor device-resident (dlpack).
        return _to_jax(t)
    # Single-controller SPMD: a stride-0 numpy view replicates this tensor
    # for every rank with zero host materialization (a dense world-sized
    # copy would blow up host memory for large gradients).
    from ..ops.bridge import replicate_for_controller
    return replicate_for_controller(_to_numpy(t), process_set)


def _ps(process_set: Optional[ProcessSet]):
    return process_set


def _register(inner: int, like: torch.Tensor, out=None, postprocess=None) -> int:
    h = next(_handle_counter)
    _handles[h] = _PendingOp(inner, like.dtype, like.device, out=out,
                             postprocess=postprocess)
    return h


def synchronize(handle):
    """Wait for an async handle; returns the resulting torch tensor.

    Reference: ``horovod/torch/mpi_ops.py synchronize`` resolving the handle
    table filled by ``mpi_ops_v2.cc`` (SURVEY.md §3.2 completion path).
    """
    if isinstance(handle, (list, tuple)):
        return [synchronize(h) for h in handle]
    op = _handles.pop(handle)
    from ..ops.bridge import RaggedAsyncHandle
    if isinstance(op.inner, RaggedAsyncHandle):
        out, rsp = op.inner.synchronize()
        return (_from_numpy(np.ascontiguousarray(out), op.dtype, op.device),
                torch.from_numpy(np.ascontiguousarray(rsp)))
    res = eager.synchronize(op.inner)
    arr = eager.to_local(res)
    t = _from_numpy(np.asarray(arr), op.dtype, op.device)
    if op.postprocess is not None:
        t = op.postprocess(t)
    if op.out is not None:
        op.out.data.copy_(t.reshape(op.out.shape))
        return op.out
    return t


def poll(handle) -> bool:
    inner = _handles[handle].inner
    from ..ops.bridge import RaggedAsyncHandle
    if isinstance(inner, RaggedAsyncHandle):
        return inner.poll()
    return eager.poll(inner)


# ------------------------------------------------------------------ allreduce
def allreduce_async(tensor: torch.Tensor, name: Optional[str] = None,
                    op: ReduceOp = Average,
                    prescale_factor: Optional[float] = None,
                    postscale_factor: Optional[float] = None,
                    process_set: Optional[ProcessSet] = None,
                    compression: Optional[str] = None,
                    priority: int = 0) -> int:
    """``compression="bf16"``/``"fp16"``: wire-dtype cast fused into the
    engine's collective program; the result returns in the input dtype.
    ``priority``: coordinator drain priority (higher first; must match
    across ranks — see the engine's priority queue)."""
    inner = eager.allreduce_async(_submit(tensor, process_set), name=name, op=op,
                                  prescale_factor=prescale_factor,
                                  postscale_factor=postscale_factor,
                                  process_set=process_set,
                                  compression=compression,
                                  priority=priority)
    return _register(inner, tensor)


def allreduce(tensor: torch.Tensor, name: Optional[str] = None,
              op: ReduceOp = Average,
              prescale_factor: Optional[float] = None,
              postscale_factor: Optional[float] = None,
              process_set: Optional[ProcessSet] = None,
              compression: Optional[str] = None) -> torch.Tensor:
    return synchronize(allreduce_async(tensor, name, op, prescale_factor,
                                       postscale_factor, process_set,
                                       compression))


def allreduce_async_(tensor: torch.Tensor, name: Optional[str] = None,
                     op: ReduceOp = Average,
                     prescale_factor: Optional[float] = None,
                     postscale_factor: Optional[float] = None,
                     process_set: Optional[ProcessSet] = None) -> int:
    inner = eager.allreduce_async(_submit(tensor, process_set), name=name, op=op,
                                  prescale_factor=prescale_factor,
                                  postscale_factor=postscale_factor,
                                  process_set=process_set)
    return _register(inner, tensor, out=tensor)


def allreduce_(tensor: torch.Tensor, name: Optional[str] = None,
               op: ReduceOp = Average,
               prescale_factor: Optional[float] = None,
               postscale_factor: Optional[float] = None,
               process_set: Optional[ProcessSet] = None) -> torch.Tensor:
    return synchronize(allreduce_async_(tensor, name, op, prescale_factor,
                                        postscale_factor, process_set))


def grouped_allreduce_async(tensors: Sequence[torch.Tensor],
                            name: Optional[str] = None,
                            op: ReduceOp = Average,
                            prescale_factor: Optional[float] = None,
                            postscale_factor: Optional[float] = None,
                            process_set: Optional[ProcessSet] = None) -> List[int]:
    inners = eager.grouped_allreduce_async(
        [_submit(t, process_set) for t in tensors], name=name, op=op,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
        process_set=process_set)
    return [_register(i, t) for i, t in zip(inners, tensors)]


def grouped_allreduce(tensors: Sequence[torch.Tensor],
                      name: Optional[str] = None, op: ReduceOp = Average,
                      prescale_factor: Optional[float] = None,
                      postscale_factor: Optional[float] = None,
                      process_set: Optional[ProcessSet] = None):
    return [synchronize(h) for h in grouped_allreduce_async(
        tensors, name, op, prescale_factor, postscale_factor, process_set)]


def grouped_allreduce_async_(tensors: Sequence[torch.Tensor],
                             name: Optional[str] = None,
                             op: ReduceOp = Average,
                             prescale_factor: Optional[float] = None,
                             postscale_factor: Optional[float] = None,
                             process_set: Optional[ProcessSet] = None) -> List[int]:
    inners = eager.grouped_allreduce_async(
        [_submit(t, process_set) for t in tensors], name=name, op=op,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
        process_set=process_set)
    return [_register(i, t, out=t) for i, t in zip(inners, tensors)]


def grouped_allreduce_(tensors: Sequence[torch.Tensor],
                       name: Optional[str] = None, op: ReduceOp = Average,
                       prescale_factor: Optional[float] = None,
                       postscale_factor: Optional[float] = None,
                       process_set: Optional[ProcessSet] = None):
    return [synchronize(h) for h in grouped_allreduce_async_(
        tensors, name, op, prescale_factor, postscale_factor, process_set)]


# ------------------------------------------------------------------ allgather
def allgather_async(tensor: torch.Tensor, name: Optional[str] = None,
                    process_set: Optional[ProcessSet] = None) -> int:
    inner = eager.allgather_async(_submit(tensor, process_set), name=name,
                                  process_set=process_set)
    return _register(inner, tensor)


def allgather(tensor: torch.Tensor, name: Optional[str] = None,
              process_set: Optional[ProcessSet] = None) -> torch.Tensor:
    return synchronize(allgather_async(tensor, name, process_set))


# ------------------------------------------------------------------ broadcast
def broadcast_async(tensor: torch.Tensor, root_rank: int = 0,
                    name: Optional[str] = None,
                    process_set: Optional[ProcessSet] = None) -> int:
    inner = eager.broadcast_async(_submit(tensor, process_set), root_rank=root_rank,
                                  name=name, process_set=process_set)
    return _register(inner, tensor)


def broadcast(tensor: torch.Tensor, root_rank: int = 0,
              name: Optional[str] = None,
              process_set: Optional[ProcessSet] = None) -> torch.Tensor:
    return synchronize(broadcast_async(tensor, root_rank, name, process_set))


def broadcast_async_(tensor: torch.Tensor, root_rank: int = 0,
                     name: Optional[str] = None,
                     process_set: Optional[ProcessSet] = None) -> int:
    inner = eager.broadcast_async(_submit(tensor, process_set), root_rank=root_rank,
                                  name=name, process_set=process_set)
    return _register(inner, tensor, out=tensor)


def broadcast_(tensor: torch.Tensor, root_rank: int = 0,
               name: Optional[str] = None,
               process_set: Optional[ProcessSet] = None) -> torch.Tensor:
    return synchronize(broadcast_async_(tensor, root_rank, name, process_set))


def broadcast_object(obj, root_rank: int = 0, name: Optional[str] = None,
                     process_set: Optional[ProcessSet] = None):
    return eager.broadcast_object(obj, root_rank=root_rank, name=name,
                                  process_set=process_set)


def allgather_object(obj, name: Optional[str] = None,
                     process_set: Optional[ProcessSet] = None,
                     per_rank: Optional[bool] = None):
    """List of every rank's pickled object (reference:
    ``horovod/torch/mpi_ops.py allgather_object``)."""
    return eager.allgather_object(obj, name=name, process_set=process_set,
                                  per_rank=per_rank)


# ------------------------------------------------------------------ alltoall
def _take_my_row(t):
    """Stacked sharded results → this rank's row (shared bridge
    convention)."""
    from ..ops.bridge import take_my_row
    return take_my_row(t)


def alltoall_async(tensor: torch.Tensor, splits=None,
                   name: Optional[str] = None,
                   process_set: Optional[ProcessSet] = None) -> int:
    world = _set_size(process_set)
    if splits is not None:
        from ..ops.bridge import ragged_alltoall_async_numpy
        sp = (splits.detach().cpu().numpy()
              if isinstance(splits, torch.Tensor) else np.asarray(splits))
        inner = ragged_alltoall_async_numpy(_to_numpy(tensor), sp, name=name,
                                            process_set=process_set)
        return _register(inner, tensor)
    if tensor.shape[0] % world != 0:
        raise ValueError(
            f"alltoall with even splits needs dim0 divisible by the "
            f"process set size ({world}); got {tuple(tensor.shape)}")
    inner = eager.alltoall_async(_submit(tensor, process_set), splits=None,
                                 name=name, process_set=process_set)
    return _register(inner, tensor, postprocess=_take_my_row)


def alltoall(tensor: torch.Tensor, splits=None, name: Optional[str] = None,
             process_set: Optional[ProcessSet] = None):
    """Even splits: returns the gathered tensor.  With ``splits``: returns
    ``(output, received_splits)`` (reference ``hvd.alltoall`` ragged form)."""
    return synchronize(alltoall_async(tensor, splits, name, process_set))


# -------------------------------------------------------------- reducescatter
def reducescatter_async(tensor: torch.Tensor, name: Optional[str] = None,
                        op: ReduceOp = Sum,
                        process_set: Optional[ProcessSet] = None) -> int:
    inner = eager.reducescatter_async(_submit(tensor, process_set), name=name, op=op,
                                      process_set=process_set)
    return _register(inner, tensor, postprocess=_take_my_row)


def reducescatter(tensor: torch.Tensor, name: Optional[str] = None,
                  op: ReduceOp = Sum,
                  process_set: Optional[ProcessSet] = None) -> torch.Tensor:
    return synchronize(reducescatter_async(tensor, name, op, process_set))


# ------------------------------------------------------------------- control
def barrier(process_set: Optional[ProcessSet] = None):
    return eager.barrier(process_set=process_set)


def join() -> int:
    return eager.join()
