"""Gradient compression for the torch binding.

Parity: reference ``horovod/torch/compression.py`` — ``Compression.none`` /
``Compression.fp16`` with ``compress``/``decompress`` returning a context.
On TPU the natural wire dtype is bfloat16 (same dynamic range as fp32,
native MXU type), so ``Compression.bf16`` is added; ``fp16`` is kept for
API parity.
"""

from __future__ import annotations

import torch


class Compressor:
    # Cast-style compressors set wire_mode ("bf16"/"fp16") so the optimizer
    # routes them through the engine's fused wire compression (see
    # jax/compression.py); custom compressors keep the explicit hooks.
    wire_mode = None

    @staticmethod
    def compress(tensor: torch.Tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor: torch.Tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    @staticmethod
    def compress(tensor: torch.Tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor: torch.Tensor, ctx):
        return tensor


class FP16Compressor(Compressor):
    wire_mode = "fp16"

    @staticmethod
    def compress(tensor: torch.Tensor):
        if tensor.dtype.is_floating_point:
            return tensor.to(torch.float16), tensor.dtype
        return tensor, None

    @staticmethod
    def decompress(tensor: torch.Tensor, ctx):
        return tensor.to(ctx) if ctx is not None else tensor


class BF16Compressor(Compressor):
    wire_mode = "bf16"

    @staticmethod
    def compress(tensor: torch.Tensor):
        if tensor.dtype.is_floating_point:
            return tensor.to(torch.bfloat16), tensor.dtype
        return tensor, None

    @staticmethod
    def decompress(tensor: torch.Tensor, ctx):
        return tensor.to(ctx) if ctx is not None else tensor


class Compression:
    """Namespace matching ``hvd.Compression.{none,fp16}`` (+ TPU ``bf16``)."""
    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
