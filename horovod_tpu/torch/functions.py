"""State broadcast helpers for the torch binding.

Parity: reference ``horovod/torch/functions.py`` — ``broadcast_parameters``
(model params or state_dict), ``broadcast_optimizer_state``,
``broadcast_object``.  Used at the start of training (and after elastic
resets) so every rank starts from rank 0's state (SURVEY.md §3.4
``state.sync``).
"""

from __future__ import annotations

import torch

from . import mpi_ops


def broadcast_parameters(params, root_rank: int = 0, process_set=None):
    """In-place broadcast of parameters from ``root_rank``.

    ``params`` may be a ``model.state_dict()``, ``dict``, or an iterable of
    ``(name, tensor)`` pairs (e.g. ``model.named_parameters()``).
    """
    writeback = None
    module = None
    if isinstance(params, torch.nn.Module):
        module = params
        params = params.state_dict()
    if isinstance(params, dict):
        writeback = params
        params = sorted(params.items())
    else:
        params = list(params)

    handles = []
    non_tensor = {}
    for name, p in params:
        if isinstance(p, torch.Tensor):
            if p.dtype.is_floating_point or p.dtype.is_complex or \
                    p.dtype in (torch.int8, torch.int16, torch.int32,
                                torch.int64, torch.uint8, torch.bool):
                handles.append(mpi_ops.broadcast_async_(
                    p, root_rank=root_rank, name=f"broadcast.{name}",
                    process_set=process_set))
        else:
            non_tensor[name] = p
    for h in handles:
        mpi_ops.synchronize(h)
    if non_tensor:
        # Non-tensor entries (arbitrary picklables) ride a pickle broadcast;
        # synced values are written back into the caller's dict.  Iterables
        # of pairs give no container to write into — broadcasting them only
        # makes sense for tensors.
        synced = mpi_ops.broadcast_object(non_tensor, root_rank=root_rank,
                                          process_set=process_set)
        if module is not None:
            # state_dict() was a fresh copy; push the synced non-tensor
            # entries back into the live module (tensors already synced
            # in place through shared storage).
            writeback.update(synced)
            module.load_state_dict(writeback)
        elif writeback is not None:
            writeback.update(synced)
        else:
            raise ValueError(
                f"broadcast_parameters got non-tensor entries "
                f"{sorted(non_tensor)} in a pair iterable; pass the "
                f"state_dict itself so synced values can be written back")


def broadcast_optimizer_state(optimizer: torch.optim.Optimizer,
                              root_rank: int = 0, process_set=None):
    """Broadcast an optimizer's full state from ``root_rank``.

    The reference reconstructs per-entry scalar tensors; pickle-broadcasting
    the ``state_dict`` achieves the same contract (identical state on every
    rank) in one object broadcast + per-tensor broadcasts for determinism of
    large momentum buffers.
    """
    if isinstance(optimizer, torch.optim.LBFGS):
        raise ValueError("cannot broadcast torch.optim.LBFGS state")
    state = mpi_ops.broadcast_object(optimizer.state_dict(),
                                     root_rank=root_rank,
                                     process_set=process_set)
    if mpi_ops.basics.rank() != root_rank:
        optimizer.load_state_dict(state)


def broadcast_object(obj, root_rank: int = 0, name=None, process_set=None):
    return mpi_ops.broadcast_object(obj, root_rank=root_rank, name=name,
                                    process_set=process_set)
