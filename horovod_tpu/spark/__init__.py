"""Spark integration (reference: ``horovod/spark`` — SURVEY.md §2b P11).

``horovod_tpu.spark.run(fn, ...)`` executes ``fn`` on ``num_proc`` Spark
executors with the horovod_tpu world formed across them, mirroring
``horovod.spark.run``.  It uses Spark **barrier execution mode**: all tasks
are scheduled together and ``BarrierTaskContext.getTaskInfos()`` gives every
task the same ordered view of participant addresses, so each task derives
its rank/local_rank/controller address from the SAME gang — no cross-job
placement race (the reference achieves this with its own driver/task probe
services, §3.3; barrier mode is Spark's native equivalent).

PySpark is not part of the TPU image, so the entry point degrades to a
clear ImportError; the ``Store`` abstraction (``horovod_tpu.spark.store``)
is fully functional standalone and is what estimator-style checkpoint/log
plumbing builds on.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional

from .store import (  # noqa: F401
    GCSStore, HDFSStore, LocalStore, RemoteStore, S3Store, Store)
from .estimator import (  # noqa: F401
    JaxEstimator, JaxModel, KerasEstimator, KerasModel,
    TorchEstimator, TorchModel,
)


def run(fn: Callable, args=(), kwargs=None, num_proc: Optional[int] = None,
        start_timeout: Optional[int] = None, env=None,
        verbose: int = 1) -> List[Any]:
    """Run ``fn`` on each Spark executor with hvd initialized.

    Reference: ``horovod.spark.run`` (``horovod/spark/__init__.py``).
    """
    try:
        import pyspark  # noqa: F401
    except ImportError as exc:
        raise ImportError(
            "horovod_tpu.spark.run requires pyspark, which is not installed "
            "in this environment. Use torovodrun (horovod_tpu.runner) for "
            "direct launches, or install pyspark on a Spark cluster.") from exc
    return _run_with_spark(fn, args, kwargs or {}, num_proc, env)


def _task_env(task_id: int, addresses: List[str], port_seed: int,
              extra_env: dict) -> dict:
    """Per-task HOROVOD_* env from the barrier gang's shared address list.

    Pure function of (task_id, addresses, seed) so every task computes a
    consistent world without further coordination; split out for testing
    without pyspark.
    """
    from ..common.net import remote_ports

    hosts = [a.rsplit(":", 1)[0] for a in addresses]
    ordered: List[str] = []
    for h in hosts:
        if h not in ordered:
            ordered.append(h)
    my_host = hosts[task_id]
    local_rank = hosts[:task_id].count(my_host)
    p1, p2 = remote_ports(2, port_seed)
    env = {
        "HOROVOD_RANK": str(task_id),
        "HOROVOD_SIZE": str(len(hosts)),
        "HOROVOD_LOCAL_RANK": str(local_rank),
        "HOROVOD_LOCAL_SIZE": str(hosts.count(my_host)),
        "HOROVOD_CROSS_RANK": str(ordered.index(my_host)),
        "HOROVOD_CROSS_SIZE": str(len(ordered)),
        "HOROVOD_CONTROLLER_ADDR": hosts[0],
        "HOROVOD_CONTROLLER_PORT": str(p1),
        "HOROVOD_CONTROLLER_PORT2": str(p2),
        "HOROVOD_HOSTNAME": my_host,
    }
    env.update({k: str(v) for k, v in (extra_env or {}).items()})
    return env


def _run_with_spark(fn, args, kwargs, num_proc,
                    env):  # pragma: no cover - pyspark not in image
    import random

    from pyspark import SparkContext

    sc = SparkContext._active_spark_context
    if sc is None:
        raise RuntimeError("No active SparkContext; create one before "
                           "calling horovod_tpu.spark.run")
    num_proc = num_proc or sc.defaultParallelism
    port_seed = random.SystemRandom().randrange(1 << 30)
    extra_env = dict(env or {})

    def _task(_it):
        import os
        from pyspark import BarrierTaskContext
        ctx = BarrierTaskContext.get()
        addresses = [i.address for i in ctx.getTaskInfos()]
        os.environ.update(_task_env(ctx.partitionId(), addresses, port_seed,
                                    extra_env))
        ctx.barrier()  # everyone has the env before anyone inits
        yield fn(*args, **kwargs)

    rdd = sc.parallelize(range(num_proc), num_proc)
    return rdd.barrier().mapPartitions(_task).collect()
