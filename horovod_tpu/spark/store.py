"""Storage abstraction for Spark-style estimator workflows.

Parity: reference ``horovod/spark/common/store.py`` (SURVEY.md §2b P11):
a ``Store`` maps a run id to train-data / validation-data / checkpoint /
logs locations, with ``LocalStore`` for filesystems and a factory that
dispatches on the URL scheme.  Object-store backends (HDFS/S3/GCS/ABFS)
require their client libraries and raise a clear error when absent — on
TPU VMs the natural production store is GCS.
"""

from __future__ import annotations

import os
import shutil
from typing import Optional


class Store:
    """Run-layout contract; ``_join`` is the single per-backend hook (local
    paths with mkdir vs. plain URL joins)."""

    def _join(self, *parts) -> str:
        raise NotImplementedError

    def get_train_data_path(self, idx=None, run_id=None) -> str:
        suffix = f".{idx}" if idx is not None else ""
        parts = ([run_id] if run_id else []) + [
            "intermediate_train_data" + suffix]
        return self._join(*parts)

    def get_val_data_path(self, idx=None, run_id=None) -> str:
        suffix = f".{idx}" if idx is not None else ""
        parts = ([run_id] if run_id else []) + [
            "intermediate_val_data" + suffix]
        return self._join(*parts)

    def get_checkpoint_path(self, run_id: str) -> str:
        return self._join(run_id, "checkpoint")

    def get_logs_path(self, run_id: str) -> str:
        return self._join(run_id, "logs")

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def read(self, path: str) -> bytes:
        raise NotImplementedError

    def write(self, path: str, data: bytes):
        raise NotImplementedError

    @staticmethod
    def create(prefix_path: str, *args, **kwargs) -> "Store":
        """Factory dispatching on scheme (reference: ``Store.create``)."""
        if prefix_path.startswith(("gs://", "gcs://")):
            return GCSStore(prefix_path, *args, **kwargs)
        if prefix_path.startswith("hdfs://"):
            return HDFSStore(prefix_path, *args, **kwargs)
        if prefix_path.startswith(("s3://", "s3a://")):
            return S3Store(prefix_path, *args, **kwargs)
        if prefix_path.startswith(("abfs://", "abfss://")):
            raise NotImplementedError(
                f"Store scheme of {prefix_path!r} is not supported; use "
                f"local, hdfs://, s3://, or gs:// paths")
        return LocalStore(prefix_path, *args, **kwargs)


class LocalStore(Store):
    """Filesystem store (reference: ``LocalStore``)."""

    def __init__(self, prefix_path: str):
        self.prefix_path = prefix_path.rstrip("/")
        os.makedirs(self.prefix_path, exist_ok=True)

    def _join(self, *parts) -> str:
        path = os.path.join(self.prefix_path, *parts)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        return path

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def read(self, path: str) -> bytes:
        with open(path, "rb") as fh:
            return fh.read()

    def write(self, path: str, data: bytes):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        # pid-unique tmp: concurrent writers (e.g. every estimator worker
        # materializing the same shards) must never share a staging file.
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "wb") as fh:
            fh.write(data)
        os.replace(tmp, path)

    def delete(self, path: str):
        if os.path.isdir(path):
            shutil.rmtree(path)
        elif os.path.exists(path):
            os.unlink(path)


class RemoteStore(Store):
    """Shared object-store layout (reference: the HDFS/S3/GCS/ABFS stores
    in ``horovod/spark/common/store.py`` share one path scheme).

    The run/checkpoint/data/logs layout is identical to ``LocalStore`` but
    joined as URLs; I/O goes through a tiny filesystem adapter
    (``exists/read/write/delete`` on full URLs).  ``fs`` is injectable so
    the layout + plumbing are testable without the client library; when
    absent, :meth:`_make_fs` imports the real client and raises a clear
    ImportError if the environment lacks it (DESIGN.md "Descopes": none of
    the client libraries are in the TPU image — the remote I/O legs are
    environment-blocked, the contract is not).
    """

    def __init__(self, prefix_path: str, fs=None):
        self.prefix_path = prefix_path.rstrip("/")
        self._fs = fs if fs is not None else self._make_fs()

    def _make_fs(self):  # pragma: no cover - needs the client library
        raise NotImplementedError

    def _join(self, *parts) -> str:
        return "/".join([self.prefix_path] + [p.strip("/") for p in parts])

    def exists(self, path: str) -> bool:
        return self._fs.exists(path)

    def read(self, path: str) -> bytes:
        return self._fs.read(path)

    def write(self, path: str, data: bytes):
        self._fs.write(path, data)

    def delete(self, path: str):
        self._fs.delete(path)


class HDFSStore(RemoteStore):
    """HDFS-backed store; requires ``pyarrow`` with HDFS support."""

    def _make_fs(self):
        try:
            from pyarrow import fs as pafs
            hdfs = pafs.HadoopFileSystem.from_uri(self.prefix_path)
        except Exception as exc:
            # pyarrow absent, or present without libhdfs / a reachable
            # cluster — either way the dependency is missing here.
            raise ImportError(
                "HDFSStore requires pyarrow with libhdfs and a reachable "
                "HDFS cluster, which this environment lacks; pass fs= "
                "explicitly or use a LocalStore") from exc
        if isinstance(hdfs, tuple):  # pragma: no cover - from_uri variants
            hdfs = hdfs[0]
        return _ArrowFS(hdfs)  # pragma: no cover - needs a live cluster


class S3Store(RemoteStore):
    """S3-backed store; requires ``boto3``."""

    def _make_fs(self):  # pragma: no cover - needs boto3 + credentials
        try:
            import boto3  # noqa: F401
        except ImportError as exc:
            raise ImportError(
                "S3Store requires boto3, which is not installed in this "
                "environment; pass fs= explicitly or use a LocalStore"
            ) from exc
        return _Boto3FS(boto3.client("s3"))


class GCSStore(RemoteStore):
    """GCS-backed store (the natural production store on TPU VMs);
    requires ``google-cloud-storage``."""

    def _make_fs(self):  # pragma: no cover - needs GCS client + creds
        try:
            from google.cloud import storage
        except ImportError as exc:
            raise ImportError(
                "GCSStore requires google-cloud-storage, which is not "
                "installed in this environment; pass fs= explicitly or "
                "use a LocalStore") from exc
        return _GCSClientFS(storage.Client())


def _split_bucket(url: str):
    rest = url.split("://", 1)[1]
    bucket, _, key = rest.partition("/")
    return bucket, key


class _Boto3FS:  # pragma: no cover - needs boto3 + credentials
    def __init__(self, client):
        self._c = client

    def exists(self, path):
        b, k = _split_bucket(path)
        try:
            self._c.head_object(Bucket=b, Key=k)
            return True
        except Exception:
            resp = self._c.list_objects_v2(Bucket=b, Prefix=k.rstrip("/")
                                           + "/", MaxKeys=1)
            return resp.get("KeyCount", 0) > 0

    def read(self, path):
        b, k = _split_bucket(path)
        return self._c.get_object(Bucket=b, Key=k)["Body"].read()

    def write(self, path, data):
        b, k = _split_bucket(path)
        self._c.put_object(Bucket=b, Key=k, Body=data)

    def delete(self, path):
        b, k = _split_bucket(path)
        resp = self._c.list_objects_v2(Bucket=b, Prefix=k)
        for obj in resp.get("Contents", []):
            self._c.delete_object(Bucket=b, Key=obj["Key"])


class _GCSClientFS:  # pragma: no cover - needs GCS client + creds
    def __init__(self, client):
        self._c = client

    def _blob(self, path):
        b, k = _split_bucket(path)
        return self._c.bucket(b).blob(k)

    def exists(self, path):
        if self._blob(path).exists():
            return True
        b, k = _split_bucket(path)
        return any(True for _ in self._c.list_blobs(
            b, prefix=k.rstrip("/") + "/", max_results=1))

    def read(self, path):
        return self._blob(path).download_as_bytes()

    def write(self, path, data):
        self._blob(path).upload_from_string(data)

    def delete(self, path):
        b, k = _split_bucket(path)
        for blob in self._c.list_blobs(b, prefix=k):
            blob.delete()


class _ArrowFS:  # pragma: no cover - needs pyarrow HDFS + cluster
    def __init__(self, fs):
        self._fs = fs

    @staticmethod
    def _path(url):
        return "/" + url.split("://", 1)[1].split("/", 1)[1]

    def exists(self, path):
        from pyarrow import fs as pafs
        info = self._fs.get_file_info(self._path(path))
        return info.type != pafs.FileType.NotFound

    def read(self, path):
        with self._fs.open_input_stream(self._path(path)) as fh:
            return fh.read()

    def write(self, path, data):
        p = self._path(path)
        parent = p.rsplit("/", 1)[0]
        self._fs.create_dir(parent, recursive=True)
        with self._fs.open_output_stream(p) as fh:
            fh.write(data)

    def delete(self, path):
        from pyarrow import fs as pafs
        p = self._path(path)
        info = self._fs.get_file_info(p)
        if info.type == pafs.FileType.Directory:
            self._fs.delete_dir(p)
        elif info.type != pafs.FileType.NotFound:
            self._fs.delete_file(p)
