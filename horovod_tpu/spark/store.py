"""Storage abstraction for Spark-style estimator workflows.

Parity: reference ``horovod/spark/common/store.py`` (SURVEY.md §2b P11):
a ``Store`` maps a run id to train-data / validation-data / checkpoint /
logs locations, with ``LocalStore`` for filesystems and a factory that
dispatches on the URL scheme.  Object-store backends (HDFS/S3/GCS/ABFS)
require their client libraries and raise a clear error when absent — on
TPU VMs the natural production store is GCS.
"""

from __future__ import annotations

import os
import shutil
from typing import Optional


class Store:
    def get_train_data_path(self, idx=None, run_id=None) -> str:
        raise NotImplementedError

    def get_val_data_path(self, idx=None, run_id=None) -> str:
        raise NotImplementedError

    def get_checkpoint_path(self, run_id: str) -> str:
        raise NotImplementedError

    def get_logs_path(self, run_id: str) -> str:
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def read(self, path: str) -> bytes:
        raise NotImplementedError

    def write(self, path: str, data: bytes):
        raise NotImplementedError

    @staticmethod
    def create(prefix_path: str, *args, **kwargs) -> "Store":
        """Factory dispatching on scheme (reference: ``Store.create``)."""
        if prefix_path.startswith(("gs://", "gcs://")):
            return GCSStore(prefix_path, *args, **kwargs)
        if prefix_path.startswith(("hdfs://", "s3://", "s3a://", "abfs://",
                                   "abfss://")):
            raise NotImplementedError(
                f"Store scheme of {prefix_path!r} requires its client "
                f"library (not in the TPU image); use a local path or "
                f"gs:// with google-cloud-storage installed")
        return LocalStore(prefix_path, *args, **kwargs)


class LocalStore(Store):
    """Filesystem store (reference: ``LocalStore``)."""

    def __init__(self, prefix_path: str):
        self.prefix_path = prefix_path.rstrip("/")
        os.makedirs(self.prefix_path, exist_ok=True)

    def _join(self, *parts) -> str:
        path = os.path.join(self.prefix_path, *parts)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        return path

    def get_train_data_path(self, idx=None, run_id=None) -> str:
        suffix = f".{idx}" if idx is not None else ""
        parts = ([run_id] if run_id else []) + [
            "intermediate_train_data" + suffix]
        return self._join(*parts)

    def get_val_data_path(self, idx=None, run_id=None) -> str:
        suffix = f".{idx}" if idx is not None else ""
        parts = ([run_id] if run_id else []) + [
            "intermediate_val_data" + suffix]
        return self._join(*parts)

    def get_checkpoint_path(self, run_id: str) -> str:
        return self._join(run_id, "checkpoint")

    def get_logs_path(self, run_id: str) -> str:
        return self._join(run_id, "logs")

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def read(self, path: str) -> bytes:
        with open(path, "rb") as fh:
            return fh.read()

    def write(self, path: str, data: bytes):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        # pid-unique tmp: concurrent writers (e.g. every estimator worker
        # materializing the same shards) must never share a staging file.
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "wb") as fh:
            fh.write(data)
        os.replace(tmp, path)

    def delete(self, path: str):
        if os.path.isdir(path):
            shutil.rmtree(path)
        elif os.path.exists(path):
            os.unlink(path)


class GCSStore(LocalStore):
    """GCS-backed store; requires ``google-cloud-storage``."""

    def __init__(self, prefix_path: str):  # pragma: no cover - no GCS here
        try:
            from google.cloud import storage  # noqa: F401
        except ImportError as exc:
            raise ImportError(
                "GCSStore requires google-cloud-storage, which is not "
                "installed in this environment") from exc
        raise NotImplementedError(
            "GCSStore: install google-cloud-storage and mount credentials; "
            "the TPU image used for tests has no network egress")
