"""Spark Estimator API: ``fit(DataFrame) -> Model`` (reference:
``horovod/spark/torch/estimator.py``, ``horovod/spark/keras/estimator.py``,
``horovod/spark/common/params.py`` — SURVEY.md §2b P11, VERDICT missing #3).

Flow, mirroring the reference:

1. **Materialize**: the DataFrame's feature/label columns are collected and
   written as ``num_proc`` numpy shards into the :class:`Store`
   (the reference materializes Parquet via Petastorm; numpy-npz shards are
   the TPU-image equivalent — same Store layout, no Petastorm dependency).
2. **Train**: ``horovod_tpu.spark.run`` executes the train function on
   every executor; each rank reads ITS shard from the store, trains with
   cross-rank gradient averaging through the coordinator, and rank 0
   writes the final parameters to the store's checkpoint path.
3. **Model**: ``fit`` returns a transformer holding the trained
   parameters; ``transform(df)`` appends a prediction column,
   ``predict(X)`` serves numpy directly.

Backends are pluggable: the default requires pyspark (absent from the TPU
test image), so tests inject a local in-process backend — the same
seam the reference's ``backend`` param provides.

Two frontends share the plumbing: :class:`JaxEstimator` (TPU-native
flagship) and :class:`TorchEstimator` (the reference's headline API).
"""

from __future__ import annotations

import io
import pickle
import uuid
from typing import Any, Callable, List, Optional, Sequence

import numpy as np

from .store import LocalStore, Store


def _rows_to_arrays(df, feature_cols: Sequence[str],
                    label_cols: Sequence[str]):
    """DataFrame-ish → (X [N, F], y [N, L]) float32 arrays.

    Accepts a pyspark DataFrame (``select(...).collect()``), any object
    with the same shape of API (the test doubles), or a plain sequence of
    dict rows.
    """
    cols = list(feature_cols) + list(label_cols)
    if hasattr(df, "select"):
        rows = [tuple(r) for r in df.select(*cols).collect()]
    elif hasattr(df, "collect"):
        rows = [tuple(r[c] for c in cols) for r in df.collect()]
    else:
        rows = [tuple(r[c] for c in cols) for r in df]
    nf = len(feature_cols)
    if not rows:
        return (np.zeros((0, nf), np.float32),
                np.zeros((0, len(label_cols)), np.float32))
    data = np.asarray(rows, dtype=np.float32)
    return data[:, :nf], data[:, nf:]


def _write_shards(store: Store, X: np.ndarray, y: np.ndarray,
                  num_shards: int, run_id: str) -> int:
    """Round-robin partitioned materialization into the store's train-data
    paths (reference: the Petastorm parquet materialization step).

    Every shard is padded to the SAME length by wrapping around the global
    rows: ranks therefore run identical batch counts per epoch, which the
    lock-step collective schedule requires (unequal counts would leave one
    rank blocking in an allreduce its peers never join).  Paths are
    namespaced by ``run_id`` so concurrent fits sharing a store cannot
    overwrite each other's shards.
    """
    per = max(1, -(-len(X) // num_shards))      # ceil, >= 1 row per shard
    for i in range(num_shards):
        idxs = [(i + k * num_shards) % len(X) for k in range(per)]
        buf = io.BytesIO()
        np.savez(buf, X=X[idxs], y=y[idxs])
        store.write(store.get_train_data_path(i, run_id=run_id),
                    buf.getvalue())
    return num_shards


def _read_shard(store: Store, idx: int, run_id: str):
    data = np.load(io.BytesIO(
        store.read(store.get_train_data_path(idx, run_id=run_id))))
    return data["X"], data["y"]


def _local_backend(fn: Callable[[], Any], num_proc: int, env=None) -> List:
    """In-process backend for environments without pyspark (tests / direct
    use): runs the train function once in the current single-controller
    world.  Refuses num_proc > 1 — training only shard 0 of a multi-shard
    materialization would silently drop most of the data."""
    if num_proc > 1:
        raise RuntimeError(
            "num_proc > 1 needs pyspark (the default Spark backend) or an "
            "explicitly injected backend that actually runs one process "
            "per rank; the in-process fallback would train on 1 shard of "
            f"{num_proc} and silently discard the rest")
    return [fn()]


def _spark_backend(fn: Callable[[], Any], num_proc: int, env=None) -> List:
    from . import run
    return run(fn, num_proc=num_proc, env=env)


class _EstimatorBase:
    """Shared param surface (reference: ``common/params.py``) + fit
    plumbing."""

    def __init__(self, *, feature_cols: Sequence[str],
                 label_cols: Sequence[str], store: Optional[Store] = None,
                 num_proc: Optional[int] = None, batch_size: int = 32,
                 epochs: int = 1, learning_rate: float = 0.01,
                 run_id: Optional[str] = None, backend=None, seed: int = 0,
                 verbose: int = 0):
        self.feature_cols = list(feature_cols)
        self.label_cols = list(label_cols)
        self.store = store or LocalStore("/tmp/horovod_tpu_estimator")
        self.num_proc = num_proc
        self.batch_size = batch_size
        self.epochs = epochs
        self.learning_rate = learning_rate
        self.run_id = run_id or f"run_{uuid.uuid4().hex[:8]}"
        self.backend = backend
        self.seed = seed
        self.verbose = verbose

    # Subclasses provide: _make_train_fn(num_proc) -> callable returning
    # final params on rank 0 (written to the store) and _make_model(params).

    def fit(self, df):
        num_proc = self.num_proc or 1
        X, y = _rows_to_arrays(df, self.feature_cols, self.label_cols)
        if len(X) == 0:
            raise ValueError("fit() got an empty DataFrame")
        _write_shards(self.store, X, y, num_proc, self.run_id)
        backend = self.backend
        if backend is None:
            backend = (_spark_backend if self._pyspark_available()
                       else _local_backend)
        ckpt_path = self.store.get_checkpoint_path(self.run_id)
        backend(self._make_train_fn(num_proc, ckpt_path), num_proc)
        params = pickle.loads(self.store.read(ckpt_path))
        return self._make_model(params)

    @staticmethod
    def _pyspark_available() -> bool:
        try:
            import pyspark  # noqa: F401
            return True
        except ImportError:
            return False


class _ModelBase:
    """Transformer returned by ``fit`` (reference: ``TorchModel`` /
    ``KerasModel``): holds trained params; ``transform`` appends an
    ``output_col`` prediction column, ``predict`` serves numpy."""

    def __init__(self, params, feature_cols: Sequence[str],
                 output_col: str = "prediction"):
        self.params = params
        self.feature_cols = list(feature_cols)
        self.output_col = output_col

    def predict(self, X: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def transform(self, df):
        if hasattr(df, "withColumn"):   # pyspark DataFrame
            import pyspark.sql.functions as F
            from pyspark.sql.types import DoubleType
            model = self

            @F.udf(returnType=DoubleType())
            def _predict(*features):
                x = np.asarray(features, np.float32)[None]
                return float(model.predict(x).reshape(-1)[0])

            return df.withColumn(self.output_col,
                                 _predict(*self.feature_cols))
        rows = ([{c: r[c] for c in r} for r in df.collect()]
                if hasattr(df, "collect") else
                [dict(r) for r in df])
        X = np.asarray([[r[c] for c in self.feature_cols] for r in rows],
                       np.float32)
        preds = self.predict(X).reshape(len(rows), -1)
        for r, p in zip(rows, preds):
            r[self.output_col] = float(p[0]) if p.size == 1 else p.tolist()
        return rows


# ------------------------------------------------------------------- JAX
class JaxModel(_ModelBase):
    def __init__(self, params, apply_fn, feature_cols, output_col="prediction"):
        super().__init__(params, feature_cols, output_col)
        self.apply_fn = apply_fn

    def predict(self, X: np.ndarray) -> np.ndarray:
        return np.asarray(self.apply_fn(self.params, np.asarray(X, np.float32)))


class JaxEstimator(_EstimatorBase):
    """TPU-native estimator over a (init_fn, apply_fn, loss_fn) triple.

    ``init_fn(rng, sample_x) -> params``; ``apply_fn(params, X) -> pred``;
    ``loss_fn(pred, y) -> scalar``.  Gradients are averaged across ranks
    through the coordinator every step (the reference's DistributedOptimizer
    contract), so each executor trains on its own shard and all end with
    identical parameters.
    """

    def __init__(self, *, init_fn, apply_fn, loss_fn, **kwargs):
        super().__init__(**kwargs)
        self.init_fn = init_fn
        self.apply_fn = apply_fn
        self.loss_fn = loss_fn

    def _make_train_fn(self, num_proc: int, ckpt_path: str):
        store, run_id = self.store, self.run_id
        init_fn, apply_fn, loss_fn = self.init_fn, self.apply_fn, self.loss_fn
        batch_size, epochs, lr = self.batch_size, self.epochs, self.learning_rate
        seed, verbose = self.seed, self.verbose

        def train():
            import jax
            import jax.numpy as jnp
            import optax
            import horovod_tpu as hvd

            if not hvd.is_initialized():
                hvd.init()
            rank = hvd.rank()
            shard = rank if num_proc > 1 else 0
            X, y = _read_shard(store, shard, run_id)
            params = init_fn(jax.random.PRNGKey(seed), X[:1])
            # Identical start everywhere (reference: broadcast_parameters).
            from ..ops.eager import broadcast_pytree
            params = broadcast_pytree(params, root_rank=0)
            opt = optax.sgd(lr)
            opt_state = opt.init(params)

            @jax.jit
            def local_grads(params, xb, yb):
                def batch_loss(p):
                    return jnp.mean(loss_fn(apply_fn(p, xb), yb))
                return jax.value_and_grad(batch_loss)(params)

            losses = []
            for epoch in range(epochs):
                for off in range(0, len(X), batch_size):
                    xb, yb = X[off:off + batch_size], y[off:off + batch_size]
                    loss, grads = local_grads(params, xb, yb)
                    grads = _eager_allreduce_pytree(grads)
                    updates, opt_state = opt.update(grads, opt_state, params)
                    params = optax.apply_updates(params, updates)
                    losses.append(float(loss))
                if verbose:
                    print(f"[estimator] rank={rank} epoch={epoch} "
                          f"loss={losses[-1]:.4f}")
            if rank == 0:
                host = jax.tree_util.tree_map(np.asarray, params)
                store.write(ckpt_path, pickle.dumps(host))
            hvd.barrier()
            return losses[-1]

        return train

    def _make_model(self, params):
        return JaxModel(params, self.apply_fn, self.feature_cols)


def _eager_allreduce_pytree(tree):
    """Average a gradient pytree across ranks through the coordinator
    (compress-free minimal version of the torch/TF bindings' hook path)."""
    import jax
    import horovod_tpu as hvd
    from ..ops.bridge import submit_numpy

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    arrays = [np.asarray(l) for l in leaves]
    outs = hvd.grouped_allreduce(
        [submit_numpy(a) for a in arrays], name="estimator.grads",
        op=hvd.Average)
    outs = [np.asarray(hvd.to_local(o)).reshape(a.shape)
            for o, a in zip(outs, arrays)]
    return jax.tree_util.tree_unflatten(treedef, outs)


# ----------------------------------------------------------------- Torch
class TorchModel(_ModelBase):
    def __init__(self, state_dict, model_factory, feature_cols,
                 output_col="prediction"):
        super().__init__(state_dict, feature_cols, output_col)
        self.model_factory = model_factory

    def predict(self, X: np.ndarray) -> np.ndarray:
        import torch
        model = getattr(self, "_model", None)
        if model is None:
            # Built once and reused: the pyspark transform UDF calls
            # predict per ROW — rebuilding the module each time would
            # construct millions of modules on a real DataFrame.
            model = self.model_factory()
            model.load_state_dict(self.params)
            model.eval()
            self._model = model
        with torch.no_grad():
            return model(torch.from_numpy(
                np.asarray(X, np.float32))).numpy()


class TorchEstimator(_EstimatorBase):
    """Reference-parity estimator (``horovod/spark/torch/estimator.py``):
    ``model_factory`` builds the torch module, ``loss`` maps
    ``(pred, target) -> scalar``; training runs under the torch binding's
    DistributedOptimizer so gradients average across executors."""

    def __init__(self, *, model_factory, loss, **kwargs):
        super().__init__(**kwargs)
        self.model_factory = model_factory
        self.loss = loss

    def _make_train_fn(self, num_proc: int, ckpt_path: str):
        store, run_id = self.store, self.run_id
        model_factory, loss_fn = self.model_factory, self.loss
        batch_size, epochs, lr = self.batch_size, self.epochs, self.learning_rate
        seed, verbose = self.seed, self.verbose

        def train():
            import torch
            import horovod_tpu as hvd
            import horovod_tpu.torch as tvd

            if not hvd.is_initialized():
                hvd.init()
            rank = tvd.rank()
            shard = rank if num_proc > 1 else 0
            X, y = _read_shard(store, shard, run_id)
            torch.manual_seed(seed)
            model = model_factory()
            tvd.broadcast_parameters(model.state_dict(), root_rank=0)
            opt = tvd.DistributedOptimizer(
                torch.optim.SGD(model.parameters(), lr=lr),
                named_parameters=model.named_parameters())
            last = 0.0
            for epoch in range(epochs):
                for off in range(0, len(X), batch_size):
                    xb = torch.from_numpy(X[off:off + batch_size])
                    yb = torch.from_numpy(y[off:off + batch_size])
                    opt.zero_grad()
                    loss = loss_fn(model(xb), yb)
                    loss.backward()
                    opt.step()
                    last = float(loss.detach())
                if verbose:
                    print(f"[estimator] rank={rank} epoch={epoch} "
                          f"loss={last:.4f}")
            if rank == 0:
                store.write(ckpt_path, pickle.dumps(model.state_dict()))
            tvd.barrier()
            return last

        return train

    def _make_model(self, state_dict):
        return TorchModel(state_dict, self.model_factory, self.feature_cols)


# ----------------------------------------------------------------- Keras
class KerasModel(_ModelBase):
    """Transformer for a fitted Keras model (reference:
    ``horovod/spark/keras/estimator.py KerasModel``): ``params`` is the
    ``get_weights()`` list; the module is rebuilt once per process."""

    def __init__(self, weights, model_factory, feature_cols,
                 output_col="prediction"):
        super().__init__(weights, feature_cols, output_col)
        self.model_factory = model_factory

    def predict(self, X: np.ndarray) -> np.ndarray:
        model = getattr(self, "_model", None)
        if model is None:
            model = self.model_factory()
            model(np.zeros_like(np.asarray(X, np.float32)[:1]))  # build
            model.set_weights(self.params)
            self._model = model
        return np.asarray(model(np.asarray(X, np.float32), training=False))


class KerasEstimator(_EstimatorBase):
    """Reference-parity Keras estimator (``horovod/spark/keras/``):
    ``model_factory`` builds the (uncompiled) ``keras.Model``; ``loss`` is
    a Keras loss (string or callable).  Each executor compiles with the
    binding's ``DistributedOptimizer``, broadcasts rank 0's initial
    weights, and fits its own shard — the Horovod Keras recipe run by the
    Spark backend.  ``optimizer_factory`` (optional) builds the inner
    Keras optimizer; default SGD(learning_rate).

    Lightning variant: descoped — see DESIGN.md (lightning is not in the
    image); ``TorchEstimator`` covers the torch path.
    """

    def __init__(self, *, model_factory, loss, optimizer_factory=None,
                 **kwargs):
        super().__init__(**kwargs)
        self.model_factory = model_factory
        self.loss = loss
        self.optimizer_factory = optimizer_factory

    def _make_train_fn(self, num_proc: int, ckpt_path: str):
        store, run_id = self.store, self.run_id
        model_factory, loss = self.model_factory, self.loss
        opt_factory = self.optimizer_factory
        batch_size, epochs, lr = self.batch_size, self.epochs, self.learning_rate
        seed, verbose = self.seed, self.verbose

        def train():
            import keras
            import horovod_tpu as hvd
            import horovod_tpu.keras as khvd

            khvd.init()
            rank = khvd.rank()
            shard = rank if num_proc > 1 else 0
            X, y = _read_shard(store, shard, run_id)
            keras.utils.set_random_seed(seed)
            model = model_factory()
            opt = (opt_factory() if opt_factory is not None
                   else keras.optimizers.SGD(learning_rate=lr))
            model.compile(optimizer=khvd.DistributedOptimizer(opt), loss=loss)
            model(X[:1])  # build variables before broadcasting them
            khvd.broadcast_global_variables(model, root_rank=0)
            hist = model.fit(X, y, batch_size=batch_size, epochs=epochs,
                             verbose=verbose if rank == 0 else 0)
            if rank == 0:
                store.write(ckpt_path, pickle.dumps(model.get_weights()))
            hvd.barrier()
            return float(hist.history["loss"][-1])

        return train

    def _make_model(self, weights):
        return KerasModel(weights, self.model_factory, self.feature_cols)
