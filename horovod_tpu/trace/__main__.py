"""``python -m horovod_tpu.trace`` — merge per-rank trace files into one
perfetto/chrome trace, and report the critical path (no jax required).

Usage::

    # merge explicit per-rank files
    python -m horovod_tpu.trace /tmp/tr.0 /tmp/tr.1 -o merged.json

    # or give the filename base the launcher suffixed (globs <base>.*)
    python -m horovod_tpu.trace /tmp/tr -o merged.json

    # critical-path report instead of (or as well as) the merged file
    python -m horovod_tpu.trace /tmp/tr --report

    # digest-level lanes from a monitor /snapshot dump (no trace files
    # needed — the MON1 side-channel already shipped per-cycle digests)
    python -m horovod_tpu.trace --from-snapshot snap.json -o merged.json

Open the merged file in https://ui.perfetto.dev or ``chrome://tracing``:
one lane per rank, flow arrows tying each negotiation cycle across ranks.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from .analyze import render_report
from .merge import (expand_inputs, load_trace_file, merge_snapshot,
                    merge_traces, write_chrome_trace)


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m horovod_tpu.trace",
        description="Merge per-rank horovod_tpu trace files into one "
                    "perfetto/chrome trace with cross-rank cycle flows")
    p.add_argument("inputs", nargs="*",
                   help="per-rank trace files, or a filename base to glob "
                        "(<base>.<rank>)")
    p.add_argument("-o", "--output", default=None,
                   help="merged chrome-trace JSON path (default: "
                        "<first input>.merged.json)")
    p.add_argument("--from-snapshot", metavar="FILE", default=None,
                   help="build digest-level lanes from a monitor /snapshot "
                        "JSON dump instead of trace files")
    p.add_argument("--report", action="store_true",
                   help="print the critical-path phase report")
    p.add_argument("--report-cycles", type=int, default=20, metavar="N",
                   help="cycles shown in the report table (default 20)")
    args = p.parse_args(argv)
    if bool(args.inputs) == bool(args.from_snapshot):
        p.error("pass per-rank trace files (or a base), or --from-snapshot")

    if args.from_snapshot:
        try:
            with open(args.from_snapshot) as fh:
                dump = json.load(fh)
        except (OSError, ValueError) as exc:
            print(f"error: could not read {args.from_snapshot}: {exc}",
                  file=sys.stderr)
            return 1
        merged = merge_snapshot(dump)
        if not merged["traceEvents"]:
            print("error: snapshot carries no trace digests (was tracing "
                  "armed with HOROVOD_TRACE and HOROVOD_MONITOR=1?)",
                  file=sys.stderr)
            return 1
        out = args.output or (args.from_snapshot + ".merged.json")
        write_chrome_trace(merged, out)
        print(f"wrote {out} ({len(merged['traceEvents'])} events, "
              f"digest-level)")
        return 0

    try:
        paths = expand_inputs(args.inputs)
    except FileNotFoundError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 1
    by_rank = {}
    for path in paths:
        try:
            rt = load_trace_file(path)
        except (OSError, ValueError) as exc:
            print(f"error: could not parse {path}: {exc}", file=sys.stderr)
            return 1
        prev = by_rank.get(rt.rank)
        if prev is not None:
            print(f"warning: duplicate rank {rt.rank} ({prev.path} and "
                  f"{rt.path}); using the later file", file=sys.stderr)
        by_rank[rt.rank] = rt
    ranks = [by_rank[r] for r in sorted(by_rank)]
    if args.report:
        print(render_report(ranks, max_cycles=args.report_cycles))
    if args.output or not args.report:
        merged = merge_traces(ranks)
        out = args.output or (paths[0] + ".merged.json")
        write_chrome_trace(merged, out)
        flows = sum(1 for e in merged["traceEvents"]
                    if e.get("ph") in ("s", "t", "f"))
        print(f"wrote {out} ({len(ranks)} rank lane(s), "
              f"{len(merged['traceEvents'])} events, {flows} flow points)")
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:     # |head closed stdout — not an error
        sys.exit(0)
