"""Span core of the distributed collective tracer (no jax imports).

A gradient's latency in the background-coordinator design is spread across
five host-side phases that the per-rank chrome timeline (N10) and the
monitor's scalar counters cannot attribute:

    queue       enqueue          -> first cycle drain
    negotiation first drain      -> globally-ready verdict
    copy_in     ready            -> fused program dispatched (the fusion
                                    copy-in / program fetch+launch)
    reduce      dispatch         -> device results settled (the collective
                                    itself, as the host observes it)
    drain       settle begin     -> waiter released (done.set)

The engine stamps monotonic timestamps at each boundary into a
:class:`TensorSpan` claimed from a preallocated ring (:class:`TraceRecorder`)
— zero allocation on the hot path (span objects are reused in place), and
strictly zero cost when tracing is disarmed (``engine.tracer is None``; every
stamp site is a single attribute check, the same contract the timeline and
monitor hooks follow).

Cross-rank correlation key: the **negotiation cycle id** (the controller's
lock-step round counter, identical on every rank for the same round — the
single-controller engine falls back to its local cycle index) plus the
response-cache **slot id** when one is known.  The merge tool
(``python -m horovod_tpu.trace``) joins per-rank trace files on the cycle id
and draws flow arrows tying the same cycle across ranks' lanes.

Compact per-cycle digests (:meth:`TraceRecorder.digest`) ride the existing
MON1 monitor side-channel inside the agent's JSON snapshot — interval-gated,
size-capped (``DIGEST_*`` caps below), and version-safe (old peers ignore
unknown snapshot keys).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

# Phase names, in lifecycle order.  The wire/digest/JSON key order
# everywhere else follows this tuple.
PHASES = ("queue", "negotiation", "copy_in", "reduce", "drain")

# Sub-legs of the ``reduce`` phase for two-level (hierarchical) dispatches
# (ISSUE 17): the host cannot stamp inside one XLA launch, so the engine
# stamps each hier span with the MODELED cross-link share of its wire time
# (``parallel.topology.cross_fraction`` — DCN bytes over total bytes) and
# the recorder splits the measured reduce duration accordingly.  Flat
# spans carry cross_frac 0.0 and never touch the leg accumulators, so the
# legs partition exactly the hier share of ``reduce``:
#     reduce_intra  ICI legs (intra-slice reduce-scatter + allgather)
#     reduce_cross  DCN leg  (cross-slice allreduce over the leader ring)
REDUCE_LEGS = ("reduce_intra", "reduce_cross")

# Span stamp keys on the wire (writer span lines), in lifecycle order:
# enqueue, drain, ready, launch, result, finished.  PHASES[i] spans
# STAMPS[i] -> STAMPS[i+1].  THE single definition — the writer, the merge
# tool and the analyzer all key off this tuple.
STAMPS = ("e", "d", "r", "l", "x", "f")


def phases_from_stamps(stamps) -> Dict[str, float]:
    """Per-phase microseconds from the six lifecycle stamps (monotonic
    seconds, 0.0 = not reached), carrying the last reached stamp forward
    past missing ones — an aborted span's elapsed time lands in the phase
    that actually contains it instead of vanishing.  THE one attribution
    rule: ``TensorSpan.phases_us`` (live recorder/digest) and the offline
    analyzer both call this, so reports can never disagree on partially
    stamped spans."""
    out: Dict[str, float] = {}
    prev = stamps[0]
    for phase, t in zip(PHASES, stamps[1:]):
        if t and prev:
            out[phase] = max(0.0, (t - prev) * 1e6)
            prev = t
        else:
            out[phase] = 0.0
    return out

# Per-phase histogram buckets (microseconds): spans the inline-kick fast
# path through a slow multi-host negotiation round.  Mirrors the monitor
# registry's default cycle-time buckets so /metrics phase histograms read
# on the same scale as hvd_cycle_time_us.
PHASE_BUCKETS_US: Tuple[float, ...] = (
    50.0, 100.0, 250.0, 500.0, 1000.0, 2500.0, 5000.0, 10000.0, 50000.0,
    250000.0, 1000000.0)

# MON1 digest caps: recent cycle rows and open-span entries shipped per
# snapshot.  The rendered digest stays well under the agent's 48KB blob
# guard (tests pin a hard byte cap).
DIGEST_MAX_CYCLES = 24
DIGEST_MAX_OPEN = 8


class TensorSpan:
    """One tensor's lifecycle through one collective (ring slot, reused).

    Timestamps are ``time.monotonic()`` seconds; 0.0 means "not reached".
    ``cycle`` is the cross-rank correlation id (negotiation round), ``slot``
    the response-cache slot (-1 unknown).
    """

    __slots__ = ("name", "cycle", "slot", "t_enqueue", "t_drain", "t_ready",
                 "t_launch", "t_result", "t_done", "error", "committed",
                 "cross_frac", "prefetch")

    def __init__(self):
        self.reset("", 0.0, 0.0)
        self.committed = True     # a fresh slot is reclaimable

    def reset(self, name: str, t_enqueue: float, t_drain: float) -> None:
        self.name = name
        self.cycle = -1
        self.slot = -1
        self.t_enqueue = t_enqueue
        self.t_drain = t_drain
        self.t_ready = 0.0
        self.t_launch = 0.0
        self.t_result = 0.0
        self.t_done = 0.0
        self.error = False
        self.committed = False
        # Modeled DCN share of the reduce phase; 0.0 = flat dispatch.
        self.cross_frac = 0.0
        # FSDP parameter-prefetch gather (ISSUE 18): stamped at backlog
        # push for PREFETCH-lane batches; its reduce time feeds the
        # "prefetch" leg of the phase breakdown (prefetch-depth tuning).
        self.prefetch = False

    def phase_name(self) -> str:
        """The phase this span is currently in (stall attribution)."""
        if self.t_done:
            return "done"
        if self.t_result:
            return "drain"
        if self.t_launch:
            return "reduce"
        if self.t_ready:
            return "copy_in"
        if self.t_drain:
            return "negotiation"
        return "queue"

    def phases_us(self) -> Dict[str, float]:
        """Per-phase durations in microseconds, over the stamped prefix of
        the lifecycle (an aborted span yields zeros past its last stamp).
        The sum equals ``lifecycle_us`` exactly when every stamp landed."""
        return phases_from_stamps((self.t_enqueue, self.t_drain,
                                   self.t_ready, self.t_launch,
                                   self.t_result, self.t_done))

    def lifecycle_us(self) -> float:
        end = self.t_done or self.t_result or self.t_launch or \
            self.t_ready or self.t_drain
        start = self.t_enqueue or self.t_drain
        return max(0.0, (end - start) * 1e6) if end and start else 0.0


class CycleRecord:
    """One coordinator cycle's stamps plus the per-phase sums of the spans
    it carried (filled in as those spans commit — possibly cycles later,
    when the in-flight window is deep)."""

    __slots__ = ("cycle", "t0", "t_drain", "t_ready", "t_dispatch",
                 "n_tensors", "negotiation_us", "phase_us", "n_committed")

    def __init__(self, cycle: int, t0: float, t_drain: float, t_ready: float,
                 t_dispatch: float, n_tensors: int, negotiation_us: float):
        self.cycle = cycle
        self.t0 = t0
        self.t_drain = t_drain
        self.t_ready = t_ready
        self.t_dispatch = t_dispatch
        self.n_tensors = n_tensors
        self.negotiation_us = negotiation_us
        self.phase_us = [0.0] * len(PHASES)
        self.n_committed = 0

    def digest_row(self) -> list:
        """Compact wire row: [cycle, n_tensors, q, neg, cpy, red, drn] —
        phase sums rounded to whole microseconds."""
        return [self.cycle, self.n_tensors] + \
            [int(round(v)) for v in self.phase_us]


class TraceRecorder:
    """Preallocated span ring + phase accumulators + optional file writer.

    One recorder per engine; built by :func:`horovod_tpu.trace.maybe_install`
    when ``HOROVOD_TRACE`` arms tracing.  ``begin`` runs on the cycle thread;
    ``commit`` on the cycle thread or the in-flight watcher — both take one
    short lock.  Ring slots are recycled oldest-committed-first; if every
    scanned slot is still open (pathologically deep in-flight windows) the
    claim is dropped and counted, never blocked.
    """

    # Bounded forward scan for a reclaimable slot before dropping a claim.
    _SCAN = 64

    def __init__(self, capacity: int = 4096, cycle_capacity: int = 512,
                 writer=None, rank: int = 0):
        self.rank = int(rank)
        self.capacity = max(16, int(capacity))
        self.cycle_capacity = max(16, int(cycle_capacity))
        self.buckets = PHASE_BUCKETS_US
        self._writer = writer
        self._lock = threading.Lock()
        self._ring: List[TensorSpan] = [TensorSpan()
                                        for _ in range(self.capacity)]
        self._next = 0
        self.dropped = 0
        self.spans_committed = 0
        # Per-phase accumulators: sum_us, count, per-bucket counts
        # (len(buckets)+1, last = +Inf overflow).
        self._phase_sum = {p: 0.0 for p in PHASES}
        self._phase_buckets = {p: [0] * (len(self.buckets) + 1)
                               for p in PHASES}
        # Two-level reduce legs (REDUCE_LEGS): fed only by spans whose
        # cross_frac > 0 — the flat path never touches these, so their
        # absence from a digest proves no hier dispatch happened.
        self._leg_sum = {p: 0.0 for p in REDUCE_LEGS}
        self._leg_buckets = {p: [0] * (len(self.buckets) + 1)
                             for p in REDUCE_LEGS}
        self.leg_spans = 0
        # FSDP prefetch leg (ISSUE 18): reduce-phase time of PREFETCH-lane
        # gathers, keyed "prefetch" in phase_histograms once any commits —
        # the phase-breakdown signal HOROVOD_PREFETCH_DEPTH tunes against.
        self._prefetch_sum = 0.0
        self._prefetch_buckets = [0] * (len(self.buckets) + 1)
        self.prefetch_spans = 0
        self.lifecycle_us_total = 0.0
        # Recent cycles, newest last; _cycle_by_id lets late span commits
        # find their cycle's aggregate.
        self._cycles: List[CycleRecord] = []
        self._cycle_by_id: Dict[int, CycleRecord] = {}
        # Wall/monotonic anchor pair: maps this process's monotonic stamps
        # onto a shareable time base for the cross-rank merge.
        self.anchor_wall = time.time()
        self.anchor_mono = time.monotonic()
        if writer is not None:
            writer.header(rank=self.rank, anchor_wall=self.anchor_wall,
                          anchor_mono=self.anchor_mono)

    # ------------------------------------------------------------ recording
    def begin(self, name: str, t_enqueue: float,
              t_drain: float) -> Optional[TensorSpan]:
        """Claim a ring slot for a tensor entering negotiation.  Returns
        None (claim dropped, counted) when no committed slot is found
        within the bounded scan."""
        with self._lock:
            for _ in range(min(self._SCAN, self.capacity)):
                span = self._ring[self._next]
                self._next = (self._next + 1) % self.capacity
                if span.committed:
                    span.reset(name, t_enqueue, t_drain)
                    return span
            self.dropped += 1
            return None

    def commit(self, span: Optional[TensorSpan]) -> None:
        """Finalize a span: accumulate its phases, fold them into its
        cycle's aggregate, emit it to the trace file.  Idempotent; must
        never raise past its own guard (callers sit on settle paths)."""
        if span is None or span.committed:
            return
        phases = span.phases_us()
        w = self._writer
        record = None
        with self._lock:
            if span.committed:          # racing commit lost
                return
            if w is not None:
                # Snapshot BEFORE flipping committed: the flip makes the
                # slot reclaimable, and a concurrent begin() (which only
                # recycles committed slots, under this lock) could reset
                # the fields mid-write otherwise.
                record = (span.name, span.cycle, span.slot, span.t_enqueue,
                          span.t_drain, span.t_ready, span.t_launch,
                          span.t_result, span.t_done, span.error,
                          span.cross_frac)
            span.committed = True
            self.spans_committed += 1
            self.lifecycle_us_total += span.lifecycle_us()
            for p, v in phases.items():
                self._phase_sum[p] += v
                counts = self._phase_buckets[p]
                for i, le in enumerate(self.buckets):
                    if v <= le:
                        counts[i] += 1
                        break
                else:
                    counts[-1] += 1
            frac = span.cross_frac
            if frac > 0.0:
                # Split the measured reduce duration into the modeled
                # ICI/DCN legs; together they re-add to reduce exactly.
                self.leg_spans += 1
                red = phases["reduce"]
                for leg, v in ((REDUCE_LEGS[0], red * (1.0 - frac)),
                               (REDUCE_LEGS[1], red * frac)):
                    self._leg_sum[leg] += v
                    counts = self._leg_buckets[leg]
                    for i, le in enumerate(self.buckets):
                        if v <= le:
                            counts[i] += 1
                            break
                    else:
                        counts[-1] += 1
            if span.prefetch:
                self.prefetch_spans += 1
                v = phases["reduce"]
                self._prefetch_sum += v
                counts = self._prefetch_buckets
                for i, le in enumerate(self.buckets):
                    if v <= le:
                        counts[i] += 1
                        break
                else:
                    counts[-1] += 1
            rec = self._cycle_by_id.get(span.cycle)
            if rec is not None:
                rec.n_committed += 1
                for i, p in enumerate(PHASES):
                    rec.phase_us[i] += phases[p]
        if record is not None:
            w.span_record(*record)

    def cycle(self, cycle: int, t0: float, t_drain: float, t_ready: float,
              t_dispatch: float, n_tensors: int,
              negotiation_us: float) -> None:
        """Record one coordinator cycle that carried tensors."""
        rec = CycleRecord(cycle, t0, t_drain, t_ready, t_dispatch,
                          n_tensors, negotiation_us)
        with self._lock:
            self._cycles.append(rec)
            self._cycle_by_id[cycle] = rec
            if len(self._cycles) > self.cycle_capacity:
                old = self._cycles.pop(0)
                self._cycle_by_id.pop(old.cycle, None)
        w = self._writer
        if w is not None:
            w.cycle(rec)

    # -------------------------------------------------------------- reading
    def open_spans(self, limit: int = DIGEST_MAX_OPEN) -> Dict[str, str]:
        """name -> current phase for in-progress spans (stall/digest)."""
        out: Dict[str, str] = {}
        with self._lock:
            for span in self._ring:
                if not span.committed:
                    out[span.name] = span.phase_name()
                    if len(out) >= limit:
                        break
        return out

    def phase_histograms(self) -> Dict[str, tuple]:
        """phase -> (bucket_counts, sum_us, count) cumulative totals, the
        payload the monitor collector mirrors into registry histograms.
        The two-level reduce legs (REDUCE_LEGS) appear as extra keys once
        a hierarchical dispatch commits — the collector mirrors whatever
        keys arrive, so ``hvd_trace_reduce_intra_us`` /
        ``hvd_trace_reduce_cross_us`` materialize exactly when the
        two-level path engages."""
        with self._lock:
            out = {p: (list(self._phase_buckets[p]), self._phase_sum[p],
                       sum(self._phase_buckets[p])) for p in PHASES}
            if self.leg_spans:
                for p in REDUCE_LEGS:
                    out[p] = (list(self._leg_buckets[p]), self._leg_sum[p],
                              sum(self._leg_buckets[p]))
            if self.prefetch_spans:
                out["prefetch"] = (list(self._prefetch_buckets),
                                   self._prefetch_sum,
                                   sum(self._prefetch_buckets))
            return out

    def phase_summary(self) -> dict:
        """Mean per-phase microseconds + mean lifecycle — the bench.py
        per-line breakdown.  ``phase_sum_us`` ~= ``cycle_us`` whenever all
        five stamps landed (the consistency the acceptance test pins)."""
        with self._lock:
            n = self.spans_committed
            if not n:
                return {"spans": 0, "phases_us": None, "cycle_us": None,
                        "phase_sum_us": None}
            phases = {p: round(self._phase_sum[p] / n, 2) for p in PHASES}
            out = {"spans": n, "phases_us": phases,
                   "cycle_us": round(self.lifecycle_us_total / n, 2),
                   "phase_sum_us": round(sum(phases.values()), 2)}
            if self.leg_spans:
                out["leg_spans"] = self.leg_spans
                out["legs_us"] = {
                    p: round(self._leg_sum[p] / self.leg_spans, 2)
                    for p in REDUCE_LEGS}
            return out

    def digest(self) -> dict:
        """Compact cross-rank digest for the MON1 monitor snapshot."""
        with self._lock:
            cycles = [rec.digest_row()
                      for rec in self._cycles[-DIGEST_MAX_CYCLES:]]
            phases = {p: [int(round(self._phase_sum[p])),
                          sum(self._phase_buckets[p])] for p in PHASES}
            legs = {p: [int(round(self._leg_sum[p])), self.leg_spans]
                    for p in REDUCE_LEGS} if self.leg_spans else None
            n, total = self.spans_committed, self.lifecycle_us_total
        out = {"v": 1, "spans": n, "phases": phases, "cycles": cycles,
               "dropped": self.dropped}
        if legs:
            # Appears only once the two-level path engaged; old peers
            # ignore unknown digest keys (version-safe).
            out["legs"] = legs
        if n:
            out["cycle_us"] = round(total / n, 1)
        open_ = self.open_spans()
        if open_:
            out["open"] = open_
        return out

    def close(self) -> None:
        w, self._writer = self._writer, None
        if w is not None:
            w.close()
