"""Critical-path analysis over merged spans (no jax imports).

Answers the question the ROADMAP's small-message latency war needs answered
before any fix can claim credit: *which host-side phase eats the cycle*.
Given one or more ranks' parsed traces (``merge.RankTrace``), attributes
per-cycle wall time to the five lifecycle phases, fleet-wide:

- **per-phase summary** — count/mean/total microseconds per phase across
  every committed span (per rank and fleet);
- **per-cycle critical path** — for each negotiation cycle present on every
  rank, the *slowest* rank's phase breakdown (that rank gates the lock-step
  round, so its phases ARE the cycle's critical path), plus which rank it
  was;
- **attribution totals** — summing the critical-path breakdown over cycles:
  the microseconds each phase contributed to the run's wall time, the
  number a latency PR must move.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from .core import PHASES, REDUCE_LEGS, STAMPS, phases_from_stamps


def _span_phases_us(span: dict) -> Dict[str, float]:
    """Phase durations from a span line's stamps — the SAME carry-forward
    rule the live recorder applies (core.phases_from_stamps), so offline
    reports agree with the MON1 digests on partially stamped spans."""
    return phases_from_stamps([span.get(k, 0.0) for k in STAMPS])


def _span_legs_us(span: dict, reduce_us: float) -> Optional[Dict[str, float]]:
    """ICI/DCN split of a span's reduce phase, from the ``cf`` key the
    engine stamps on two-level dispatches (the modeled DCN share —
    core.REDUCE_LEGS).  None for flat spans, so leg totals attribute only
    the time the two-level path actually ran."""
    frac = float(span.get("cf", 0.0) or 0.0)
    if frac <= 0.0:
        return None
    return {REDUCE_LEGS[0]: reduce_us * (1.0 - frac),
            REDUCE_LEGS[1]: reduce_us * frac}


def phase_summary(ranks: List) -> dict:
    """Fleet + per-rank per-phase mean/total microseconds.

    When any span rode the two-level data plane, a ``legs`` block splits
    the fleet's reduce time into intra-slice (ICI) and cross-slice (DCN)
    legs — the number the crossover-picking workflow reads (DCN time is
    what a bigger HOROVOD_HIER_THRESHOLD trades against phase latency)."""
    fleet = {p: [0.0, 0] for p in PHASES}        # sum, count
    legs = {p: [0.0, 0] for p in REDUCE_LEGS}
    per_rank: Dict[int, dict] = {}
    for rt in ranks:
        mine = {p: [0.0, 0] for p in PHASES}
        for s in rt.spans:
            phases = _span_phases_us(s)
            for p, us in phases.items():
                mine[p][0] += us
                mine[p][1] += 1
                fleet[p][0] += us
                fleet[p][1] += 1
            ls = _span_legs_us(s, phases["reduce"])
            if ls is not None:
                for p, us in ls.items():
                    legs[p][0] += us
                    legs[p][1] += 1
        per_rank[rt.rank] = {
            p: {"total_us": round(v[0], 1),
                "mean_us": round(v[0] / v[1], 2) if v[1] else None}
            for p, v in mine.items()}
    out = {
        "fleet": {p: {"total_us": round(v[0], 1),
                      "mean_us": round(v[0] / v[1], 2) if v[1] else None,
                      "spans": v[1]}
                  for p, v in fleet.items()},
        "per_rank": per_rank,
    }
    if any(v[1] for v in legs.values()):
        out["legs"] = {p: {"total_us": round(v[0], 1),
                           "mean_us": round(v[0] / v[1], 2) if v[1] else None,
                           "spans": v[1]}
                       for p, v in legs.items()}
    return out


def critical_path(ranks: List, max_cycles: Optional[int] = None) -> dict:
    """Per-cycle critical-path attribution.

    For every cycle id seen on *all* ranks: per rank, sum that cycle's span
    phases; the critical rank is the one with the largest phase sum (it
    gated the lock-step round).  Returns the per-cycle rows plus the
    attribution totals over the critical rank's phases."""
    if not ranks:
        return {"cycles": [], "attributed_us": None, "slowest_counts": {}}
    # rank -> cycle -> phase sums
    by_rank: Dict[int, Dict[int, Dict[str, float]]] = {}
    for rt in ranks:
        table: Dict[int, Dict[str, float]] = {}
        for s in rt.spans:
            cid = int(s.get("c", -1))
            if cid < 0:
                continue
            agg = table.setdefault(cid, {p: 0.0 for p in PHASES})
            for p, us in _span_phases_us(s).items():
                agg[p] += us
        by_rank[rt.rank] = table
    common = None
    for table in by_rank.values():
        ids = set(table)
        common = ids if common is None else (common & ids)
    common = sorted(common or [])
    if max_cycles:
        common = common[-max_cycles:]
    rows = []
    attributed = {p: 0.0 for p in PHASES}
    slowest_counts: Dict[int, int] = {}
    for cid in common:
        slow_rank, slow_total, slow_phases = None, -1.0, None
        for rank, table in by_rank.items():
            phases = table[cid]
            total = sum(phases.values())
            if total > slow_total:
                slow_rank, slow_total, slow_phases = rank, total, phases
        rows.append({"cycle": cid, "slowest_rank": slow_rank,
                     "total_us": round(slow_total, 1),
                     "phases_us": {p: round(v, 1)
                                   for p, v in slow_phases.items()}})
        slowest_counts[slow_rank] = slowest_counts.get(slow_rank, 0) + 1
        for p, v in slow_phases.items():
            attributed[p] += v
    return {
        "cycles": rows,
        "attributed_us": {p: round(v, 1) for p, v in attributed.items()},
        "slowest_counts": slowest_counts,
    }


def render_report(ranks: List, max_cycles: int = 20) -> str:
    """Human-readable critical-path report for the CLI (``--report``)."""
    summary = phase_summary(ranks)
    cp = critical_path(ranks)
    lines: List[str] = []
    lines.append(f"ranks: {sorted(rt.rank for rt in ranks)}   spans: "
                 f"{sum(len(rt.spans) for rt in ranks)}   common cycles: "
                 f"{len(cp['cycles'])}")
    lines.append("")
    lines.append("fleet per-phase means (us):")
    header = "  " + "".join(f"{p:>14}" for p in PHASES)
    lines.append(header)
    lines.append("  " + "".join(
        f"{(summary['fleet'][p]['mean_us'] or 0):>14.2f}" for p in PHASES))
    legs = summary.get("legs")
    if legs:
        lines.append("")
        lines.append("two-level reduce legs (ICI vs DCN, modeled split):")
        for p in REDUCE_LEGS:
            v = legs[p]
            link = "ICI" if p == REDUCE_LEGS[0] else "DCN"
            lines.append(f"  {p:>14}  {v['total_us']:>12.1f} us total  "
                         f"{(v['mean_us'] or 0):>10.2f} us mean  [{link}]")
    att = cp["attributed_us"]
    if att:
        total = sum(att.values()) or 1.0
        lines.append("")
        lines.append("critical-path attribution (slowest rank per cycle):")
        for p in PHASES:
            pct = 100.0 * att[p] / total
            bar = "#" * int(round(pct / 2))
            lines.append(f"  {p:>12}  {att[p]:>12.1f} us  {pct:5.1f}%  {bar}")
        lines.append(f"  {'total':>12}  {total:>12.1f} us")
        counts = ", ".join(f"rank {r}: {n}" for r, n in
                           sorted(cp["slowest_counts"].items()))
        lines.append(f"  slowest-rank counts: {counts}")
    if cp["cycles"]:
        lines.append("")
        lines.append(f"last {min(max_cycles, len(cp['cycles']))} cycles "
                     f"(slowest rank, us):")
        lines.append("  cycle  rank  " + "".join(f"{p:>12}" for p in PHASES))
        for row in cp["cycles"][-max_cycles:]:
            lines.append(
                f"  {row['cycle']:>5}  {row['slowest_rank']:>4}  " + "".join(
                    f"{row['phases_us'][p]:>12.1f}" for p in PHASES))
    return "\n".join(lines)
