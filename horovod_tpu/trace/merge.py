"""Cross-rank trace merge: per-rank JSONL files -> one perfetto/chrome
trace (no jax imports).

Replaces eyeballing N per-rank ``HOROVOD_TIMELINE`` files: the merged view
has **one lane (process group) per rank** — a ``cycles`` thread carrying the
coordinator cycles and one thread per tensor carrying its five lifecycle
phases — plus **flow arrows tying the same negotiation cycle across
ranks** (chrome ``ph:"s"/"t"/"f"`` flow events keyed on the cycle id, the
cross-rank correlation key the spans were stamped with).

Time base: each rank's file carries a (wall, monotonic) anchor pair; every
monotonic stamp is mapped to wall time and the fleet minimum is subtracted,
so skew between hosts is bounded by wall-clock sync (the flow arrows keep
cycles correlated regardless).
"""

from __future__ import annotations

import glob
import json
import os
from typing import Dict, List, Optional

from .core import PHASES, STAMPS


class RankTrace:
    """One rank's parsed trace file."""

    def __init__(self, rank: int, anchor_wall: float, anchor_mono: float,
                 spans: List[dict], cycles: List[dict], path: str = ""):
        self.rank = rank
        self.anchor_wall = anchor_wall
        self.anchor_mono = anchor_mono
        self.spans = spans
        self.cycles = cycles
        self.path = path

    def to_wall(self, t_mono: float) -> float:
        return self.anchor_wall + (t_mono - self.anchor_mono)


def load_trace_file(path: str) -> RankTrace:
    """Parse one per-rank JSONL trace file (header + span/cycle lines)."""
    rank, aw, am = 0, 0.0, 0.0
    spans: List[dict] = []
    cycles: List[dict] = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            obj = json.loads(line)
            kind = obj.get("k")
            if kind == "h":
                rank = int(obj.get("rank", 0))
                aw = float(obj.get("anchor_wall", 0.0))
                am = float(obj.get("anchor_mono", 0.0))
            elif kind == "s":
                spans.append(obj)
            elif kind == "c":
                cycles.append(obj)
    return RankTrace(rank, aw, am, spans, cycles, path=path)


def expand_inputs(inputs: List[str]) -> List[str]:
    """Resolve CLI inputs: existing files pass through; anything else is
    treated as a per-rank filename base and globbed — strictly
    ``<base>.<rank>`` with a NUMERIC rank suffix (the launcher's scheme),
    so a previous merge's ``<base>.0.merged.json`` output sitting next to
    the per-rank files can never be swallowed as a rank trace."""
    out: List[str] = []
    for inp in inputs:
        if os.path.isfile(inp):
            out.append(inp)
            continue
        matches = [m for m in glob.glob(inp + ".*")
                   if os.path.isfile(m) and m[len(inp) + 1:].isdigit()]
        matches.sort(key=lambda m: int(m[len(inp) + 1:]))
        if not matches:
            raise FileNotFoundError(
                f"no trace file or per-rank files matching {inp!r} "
                f"(expected {inp} or {inp}.<rank>)")
        out.extend(matches)
    return out


def merge_traces(ranks: List[RankTrace]) -> dict:
    """Build the merged chrome-trace object from parsed rank traces."""
    events: List[dict] = []
    if not ranks:
        return {"traceEvents": events, "displayTimeUnit": "ms"}
    base = min(r.anchor_wall for r in ranks if r.anchor_wall) \
        if any(r.anchor_wall for r in ranks) else 0.0

    def ts(rt: RankTrace, t_mono: float) -> float:
        return max(0.0, (rt.to_wall(t_mono) - base) * 1e6)

    # cycle id -> [(rank, start_us)] for the flow arrows.
    cycle_sites: Dict[int, List[tuple]] = {}
    for rt in sorted(ranks, key=lambda r: r.rank):
        pid = rt.rank
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "args": {"name": f"rank {pid}"}})
        events.append({"name": "thread_name", "ph": "M", "pid": pid,
                       "tid": 0, "args": {"name": "cycles"}})
        for c in rt.cycles:
            t0, tx = c.get("t0", 0.0), c.get("tx", 0.0)
            if not t0:
                continue
            start = ts(rt, t0)
            dur = max(0.1, (tx - t0) * 1e6) if tx else 0.1
            events.append({
                "name": f"cycle {c['c']}", "ph": "X", "pid": pid, "tid": 0,
                "ts": round(start, 3), "dur": round(dur, 3),
                "args": {"cycle": c["c"], "tensors": c.get("n", 0),
                         "negotiation_us": c.get("neg", 0)}})
            cycle_sites.setdefault(int(c["c"]), []).append((pid, start))
        tids: Dict[str, int] = {}
        for s in rt.spans:
            name = s.get("n", "?")
            tid = tids.get(name)
            if tid is None:
                tid = tids[name] = len(tids) + 1
                events.append({"name": "thread_name", "ph": "M", "pid": pid,
                               "tid": tid, "args": {"name": name}})
            stamps = [s.get(k, 0.0) for k in STAMPS]
            for i, phase in enumerate(PHASES):
                a, b = stamps[i], stamps[i + 1]
                if not a or not b or b < a:
                    continue
                events.append({
                    "name": phase.upper(), "ph": "X", "pid": pid, "tid": tid,
                    "ts": round(ts(rt, a), 3),
                    "dur": round(max(0.1, (b - a) * 1e6), 3),
                    "args": {"cycle": s.get("c", -1),
                             "slot": s.get("slot", -1)}})

    _emit_cycle_flows(events, cycle_sites)
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def _emit_cycle_flows(events: List[dict],
                      cycle_sites: Dict[int, List[tuple]]) -> None:
    """Flow arrows tying one cycle id across rank lanes: chained in rank
    order (``s`` -> ``t``... -> ``f``), anchored just inside each rank's
    cycle slice.  Shared by the span-level and digest-level mergers so
    the flow semantics cannot drift between them."""
    for cid, sites in sorted(cycle_sites.items()):
        if len(sites) < 2:
            continue
        sites.sort()
        for i, (pid, start) in enumerate(sites):
            ph = "s" if i == 0 else ("f" if i == len(sites) - 1 else "t")
            ev = {"name": "cycle", "cat": "cycle", "ph": ph, "id": cid,
                  "pid": pid, "tid": 0, "ts": round(start + 0.05, 3)}
            if ph == "f":
                ev["bp"] = "e"
            events.append(ev)


def merge_snapshot(dump: dict) -> dict:
    """Digest-level merge from a monitor ``/snapshot`` dump: each rank's
    MON1 trace digest becomes a lane of per-cycle phase-stacked slices.

    No absolute timestamps exist at digest level, so cycles are laid out on
    a synthetic time axis (cycle id spacing = the fleet's max per-cycle
    phase sum) — phase *attribution* is exact, alignment is by cycle id.
    """
    table = dump.get("table", {})
    per_rank: Dict[int, dict] = {}
    for r, snap in table.items():
        tr = (snap or {}).get("trace")
        if tr and tr.get("cycles"):
            per_rank[int(r)] = tr
    events: List[dict] = []
    if not per_rank:
        return {"traceEvents": events, "displayTimeUnit": "ms"}
    # Synthetic axis: slot width fits the largest cycle anywhere.
    width = 1.0
    for tr in per_rank.values():
        for row in tr["cycles"]:
            width = max(width, float(sum(row[2:])))
    width *= 1.25
    cycle_ids = sorted({row[0] for tr in per_rank.values()
                        for row in tr["cycles"]})
    offset = {cid: i * width for i, cid in enumerate(cycle_ids)}
    cycle_sites: Dict[int, List[tuple]] = {}
    for rank in sorted(per_rank):
        tr = per_rank[rank]
        events.append({"name": "process_name", "ph": "M", "pid": rank,
                       "args": {"name": f"rank {rank} (digest)"}})
        events.append({"name": "thread_name", "ph": "M", "pid": rank,
                       "tid": 0, "args": {"name": "cycles"}})
        for row in tr["cycles"]:
            cid, n = int(row[0]), int(row[1])
            start = offset[cid]
            cursor = start
            for phase, us in zip(PHASES, row[2:]):
                if us <= 0:
                    continue
                events.append({
                    "name": phase.upper(), "ph": "X", "pid": rank, "tid": 0,
                    "ts": round(cursor, 3), "dur": round(float(us), 3),
                    "args": {"cycle": cid, "tensors": n}})
                cursor += float(us)
            cycle_sites.setdefault(cid, []).append((rank, start))
    _emit_cycle_flows(events, cycle_sites)
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_chrome_trace(trace: dict, path: str) -> None:
    with open(path, "w") as fh:
        json.dump(trace, fh)
