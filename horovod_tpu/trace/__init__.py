"""Distributed collective tracing (no jax imports — tier-1 purity guarded).

Follows every tensor through its five host-side lifecycle phases (queue →
negotiation → copy_in → reduce → drain), correlates ranks on the
negotiation cycle id, and merges the fleet into one perfetto view:

- :mod:`.core`    — span ring + per-phase accumulators (the engine stamps);
- :mod:`.writer`  — per-rank JSONL trace files (``HOROVOD_TRACE``);
- :mod:`.merge`   — cross-rank merge into a chrome/perfetto trace with
  per-rank lanes and cycle flow arrows (``python -m horovod_tpu.trace``);
- :mod:`.analyze` — critical-path attribution (which phase eats the cycle).

See ``docs/timeline.md`` for knobs and reading recipes.
"""

from __future__ import annotations

from .core import (DIGEST_MAX_CYCLES, DIGEST_MAX_OPEN, PHASE_BUCKETS_US,
                   PHASES, REDUCE_LEGS, CycleRecord, TensorSpan,
                   TraceRecorder)
from .writer import TraceWriter

__all__ = [
    "PHASES", "REDUCE_LEGS", "PHASE_BUCKETS_US", "DIGEST_MAX_CYCLES",
    "DIGEST_MAX_OPEN", "CycleRecord", "TensorSpan", "TraceRecorder",
    "TraceWriter", "maybe_install",
]


def maybe_install(cfg, rank: int = 0):
    """Build a :class:`TraceRecorder` when the config arms tracing
    (``HOROVOD_TRACE``), else None — the engine's ``tracer`` attribute.
    Called from the engine constructor; a None return keeps every stamp
    site a single attribute check (the strictly-zero-cost disarmed
    contract, pinned by the bench trace A/B)."""
    if not getattr(cfg, "trace", False):
        return None
    filename = getattr(cfg, "trace_filename", "") or ""
    writer = TraceWriter(filename, rank=rank) if filename else None
    return TraceRecorder(capacity=getattr(cfg, "trace_ring", 4096),
                         writer=writer, rank=rank)
