"""Per-rank trace file writer (no jax imports).

One JSONL file per rank (``HOROVOD_TRACE``; the launcher suffixes the base
with the rank, the same ``utils.timeline.per_rank_filename`` scheme the
chrome timeline uses).  Line kinds:

- header  ``{"k":"h","rank":r,"anchor_wall":...,"anchor_mono":...,"v":1}``
  — the wall/monotonic anchor pair the merge tool uses to put every rank's
  monotonic stamps on one shared time base;
- span    ``{"k":"s","n":name,"c":cycle,"slot":s,"e":...,"d":...,"r":...,
  "l":...,"x":...,"f":...,"err":0|1}`` — the six lifecycle stamps
  (enqueue, drain, ready, launch, result, finished), monotonic seconds;
- cycle   ``{"k":"c","c":cycle,"t0":...,"td":...,"tr":...,"tx":...,
  "n":count,"neg":us}``.

Writes are lock-guarded and flushed on a small line budget so a crashed
rank still leaves a usable file; ``close`` flushes the rest.  Every write
failure disables the writer (tracing must never take training down).
"""

from __future__ import annotations

import json
import threading

from ..utils.logging import get_logger

log = get_logger()

_FLUSH_EVERY = 64


class TraceWriter:
    """Append-only JSONL emitter for one rank's spans and cycles."""

    def __init__(self, filename: str, rank: int = 0):
        self.filename = filename
        self.rank = int(rank)
        self._lock = threading.Lock()
        self._pending = 0
        try:
            self._fh = open(filename, "w")
        except OSError as exc:
            log.warning("trace: cannot open %s (%s); file output disabled",
                        filename, exc)
            self._fh = None

    @property
    def enabled(self) -> bool:
        return self._fh is not None

    def _emit(self, obj: dict) -> None:
        with self._lock:
            if self._fh is None:
                return
            try:
                self._fh.write(json.dumps(obj, separators=(",", ":")) + "\n")
                self._pending += 1
                if self._pending >= _FLUSH_EVERY:
                    self._fh.flush()
                    self._pending = 0
            except OSError:
                log.exception("trace: write failed; disabling file output")
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None

    def header(self, rank: int, anchor_wall: float,
               anchor_mono: float) -> None:
        self._emit({"k": "h", "v": 1, "rank": rank,
                    "anchor_wall": anchor_wall, "anchor_mono": anchor_mono})

    def span_record(self, name, cycle, slot, t_enqueue, t_drain, t_ready,
                    t_launch, t_result, t_done, error,
                    cross_frac: float = 0.0) -> None:
        """One span line from an already-snapshotted field tuple (the
        recorder snapshots under its lock BEFORE marking the ring slot
        reclaimable — passing the live span object here would race its
        recycling).  Stamp keys follow ``core.STAMPS`` order.  ``cf``
        (modeled DCN share of the reduce phase, two-level dispatches
        only) is omitted for flat spans — old readers never see it and
        flat trace files pay zero extra bytes."""
        obj = {"k": "s", "n": name, "c": cycle, "slot": slot,
               "e": round(t_enqueue, 7), "d": round(t_drain, 7),
               "r": round(t_ready, 7), "l": round(t_launch, 7),
               "x": round(t_result, 7), "f": round(t_done, 7),
               "err": 1 if error else 0}
        if cross_frac:
            obj["cf"] = round(cross_frac, 4)
        self._emit(obj)

    def cycle(self, rec) -> None:
        self._emit({"k": "c", "c": rec.cycle, "t0": round(rec.t0, 7),
                    "td": round(rec.t_drain, 7), "tr": round(rec.t_ready, 7),
                    "tx": round(rec.t_dispatch, 7), "n": rec.n_tensors,
                    "neg": round(rec.negotiation_us, 1)})

    def close(self) -> None:
        with self._lock:
            if self._fh is None:
                return
            try:
                self._fh.flush()
                self._fh.close()
            except OSError:
                pass
            self._fh = None
