"""First-class sharded checkpointing.

The reference is thin here by design (SURVEY.md §5 "Checkpoint/resume"):
elastic ``State`` commit/restore is in-memory and durable checkpoints are
left to rank-0 framework saves in the examples.  On TPU, sharded
checkpointing is promoted to a first-class subsystem (as §5 recommends):
orbax writes each shard from the process that owns it (scales to multi-host
pods and TB-scale params), with step management and a numpy fallback when
orbax is unavailable.

Surface:
    save(dir, tree, step)          — async-capable sharded save
    restore(dir, template, step)   — restore (resharded onto the template)
    latest_step(dir)               — newest step on disk, or None
    CheckpointManager              — keep-last-N + save-interval policy
    save_state / restore_state     — elastic ``State`` integration: durable
                                     commit/resume for JaxState-style objects
"""

from __future__ import annotations

import os
import re
from typing import Any, Optional

import jax
import numpy as np

_STEP_RE = re.compile(r"^step_(\d+)$")


def _orbax():
    try:
        import orbax.checkpoint as ocp
        return ocp
    except ImportError:  # pragma: no cover - orbax is in the image
        return None


def _step_dir(directory: str, step: int) -> str:
    return os.path.join(directory, f"step_{step}")


def latest_step(directory: str) -> Optional[int]:
    if not os.path.isdir(directory):
        return None
    steps = [int(m.group(1)) for d in os.listdir(directory)
             if (m := _STEP_RE.match(d))
             and not os.path.exists(os.path.join(directory, d, ".tmp"))]
    return max(steps) if steps else None


def save(directory: str, tree: Any, step: int = 0, force: bool = True):
    """Save a pytree (params/opt_state/scalars) as checkpoint ``step``.

    Multi-host: every process calls this; orbax writes each process's
    addressable shards (the TPU-native equivalent of the reference's
    "rank 0 writes the checkpoint" — no gather, no HBM spike).
    """
    ocp = _orbax()
    path = _step_dir(directory, step)
    if ocp is not None:
        ckptr = ocp.StandardCheckpointer()
        ckptr.save(os.path.abspath(path), tree, force=force)
        ckptr.wait_until_finished()
        ckptr.close()
        return
    _numpy_save(path, tree)  # pragma: no cover - fallback


def restore(directory: str, template: Any = None,
            step: Optional[int] = None) -> Any:
    """Restore a checkpoint.  ``template`` (a pytree of arrays or
    ShapeDtypeStructs, e.g. the freshly-initialized state) drives structure
    and resharding — restoring onto a DIFFERENT mesh than the save used is
    supported, which is what elastic resume after a world-size change needs.
    """
    if step is None:
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(f"No checkpoints under {directory!r}")
    ocp = _orbax()
    path = _step_dir(directory, step)
    if ocp is not None:
        ckptr = ocp.StandardCheckpointer()
        try:
            if template is not None:
                abstract = jax.tree_util.tree_map(_abstractify, template)
                return ckptr.restore(os.path.abspath(path), abstract)
            return ckptr.restore(os.path.abspath(path))
        finally:
            ckptr.close()
    return _numpy_restore(path, template)  # pragma: no cover - fallback


def _abstractify(x):
    if isinstance(x, jax.ShapeDtypeStruct):
        return x
    if isinstance(x, jax.Array):
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
    if hasattr(x, "shape") and hasattr(x, "dtype"):
        return jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype)
    return x


class CheckpointManager:
    """Keep-last-N + interval policy (reference users get this from
    framework callbacks; here it is part of the subsystem).

    Example::

        mgr = CheckpointManager(dir, max_to_keep=3, save_interval_steps=100)
        for step in ...:
            mgr.save(step, {"params": params, "opt": opt_state})
        state = mgr.restore(template)
    """

    def __init__(self, directory: str, max_to_keep: int = 3,
                 save_interval_steps: int = 1):
        self.directory = directory
        self.max_to_keep = max_to_keep
        self.save_interval_steps = save_interval_steps
        os.makedirs(directory, exist_ok=True)

    def should_save(self, step: int) -> bool:
        return step % self.save_interval_steps == 0

    def save(self, step: int, tree: Any, force: bool = False) -> bool:
        if not force and not self.should_save(step):
            return False
        save(self.directory, tree, step)
        self._gc()
        return True

    def restore(self, template: Any = None, step: Optional[int] = None):
        return restore(self.directory, template, step)

    def latest_step(self) -> Optional[int]:
        return latest_step(self.directory)

    def all_steps(self):
        if not os.path.isdir(self.directory):
            return []
        return sorted(int(m.group(1)) for d in os.listdir(self.directory)
                      if (m := _STEP_RE.match(d)))

    def _gc(self):
        import shutil
        steps = self.all_steps()
        for s in steps[:-self.max_to_keep]:
            shutil.rmtree(_step_dir(self.directory, s), ignore_errors=True)


# ------------------------------------------------------- elastic integration
def save_state(state, directory: str, step: int = 0):
    """Durable commit of an elastic ``ObjectState``/``JaxState``: persists
    the saved (committed) attribute dict."""
    state.save()
    tree = dict(state._saved_state)
    save(directory, tree, step)


def restore_state(state, directory: str, step: Optional[int] = None):
    """Resume an elastic state from disk: loads into the state's attributes
    and its committed backup (so a later ``restore()`` rolls back to it)."""
    template = dict(state._saved_state) if state._saved_state else None
    tree = restore(directory, template, step)
    for k, v in tree.items():
        setattr(state, k, v)
    state.save()


# ------------------------------------------------------------ numpy fallback
def _numpy_save(path: str, tree: Any):  # pragma: no cover - fallback
    # The .tmp marker makes the write crash-safe: latest_step() skips any
    # step dir still carrying it (orbax writes atomically on its own).
    os.makedirs(path, exist_ok=True)
    marker = os.path.join(path, ".tmp")
    with open(marker, "w"):
        pass
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    import pickle
    np.savez(os.path.join(path, "leaves.npz"),
             *[np.asarray(l) for l in leaves])
    with open(os.path.join(path, "treedef.pkl"), "wb") as fh:
        pickle.dump(treedef, fh)
    os.unlink(marker)


def _numpy_restore(path: str, template: Any):  # pragma: no cover - fallback
    import pickle
    with open(os.path.join(path, "treedef.pkl"), "rb") as fh:
        treedef = pickle.load(fh)
    data = np.load(os.path.join(path, "leaves.npz"))
    leaves = [data[k] for k in data.files]
    return jax.tree_util.tree_unflatten(treedef, leaves)
