// TPU-native coordinator control plane: TCP negotiation over DCN.
//
// Native equivalent of the reference's controller transports
// (horovod/common/mpi/mpi_controller.cc, horovod/common/gloo/gloo_controller.cc
// — SURVEY.md §2a N2/N3/N4) with the transport swapped per SURVEY.md §5
// ("distributed communication backend"): instead of MPI gather/bcast of
// serialized Request/Response messages, a rank-0 TCP server runs lock-step
// negotiation rounds with every worker over DCN.  The data plane is NOT
// here — fused collectives execute as XLA programs over ICI; this is purely
// the out-of-graph readiness protocol (which tensors are pending on every
// rank, in what order), plus rank-0 stall tracking (N11's role).
//
// Wire protocol (all little-endian, length-prefixed frames):
//   frame  := uint32 payload_len, payload
//   C->S   := uint32 n_announce, n_announce * { uint16 required,
//                                               uint16 len, bytes name,
//                                               uint16 dlen, bytes digest,
//                                               uint16 glen, bytes group,
//                                               uint16 plen, bytes datadep }
//             (names newly enqueued on this rank since the last round;
//              `required` = number of ranks that must announce before the
//              tensor is ready — process-set size; 0 means the full world.
//              `digest` describes the submission — op|dtype|shape|root —
//              so rank 0 can reject divergent submissions (the reference
//              controller's shape/dtype consistency checks, SURVEY.md N2).
//              `group` is the announcer's local grouped-collective id ("-1"
//              for ungrouped) — NOT part of the mismatch comparison, since
//              group counters legitimately drift across ranks (uneven join
//              epochs); the server namespaces it by first-announcer rank
//              and echoes it so joined ranks preserve group batching.
//              `datadep` marks collectives that need real data from
//              specific ranks: "-1" none (reductions), "-2" every rank
//              (allgather/alltoall), or a root rank (broadcast) — if the
//              needed rank has JOINED the server answers with a per-tensor
//              error instead of fabricating data.
//              A round with nothing new sends n_announce = 0)
//   S->C   := uint32 n_ready,   n_ready * { uint16 len, bytes name,
//                                           uint16 dlen, bytes digest,
//                                           uint16 glen, bytes group }
//             uint32 n_warn,    n_warn  * { uint16 len, bytes text }
//             uint32 n_err,     n_err   * { uint16 len, bytes name,
//                                           uint16 mlen, bytes message }
//             (ready = pending on ALL ranks, in deterministic order:
//              first-announce round, then name; the digest rides along so
//              JOINED ranks can synthesize zero contributions for tensors
//              they never submitted — the reference's hvd.join() semantics;
//              warn = stall diagnoses naming the missing ranks, the
//              reference's stall_inspector output; err = per-tensor
//              negotiation failures — digest mismatch across ranks —
//              broadcast until every required rank has announced the name,
//              the reference's per-tensor error Response)
//
// join protocol: announcing the reserved name "\x1f__join__" marks the
// sender joined (reference: hvd.join, horovod/common/controller.cc's join
// handling).  Joined ranks count as implicitly ready for every world-level
// tensor.  When ALL ranks have joined, the server broadcasts the reserved
// ready entry "\x1f__all_joined__" whose digest is the last joining rank,
// then resets join state (the world resumes normal operation).
//
// Exported C ABI (ctypes-consumed by horovod_tpu/common/native.py):
//   hvdtpu_server_start(port, world) -> handle
//   hvdtpu_server_stop(handle)
//   hvdtpu_client_connect(host, port, rank, timeout_ms) -> handle
//   hvdtpu_client_round(handle, req, req_len, resp_buf, resp_cap) -> resp_len
//   hvdtpu_client_close(handle)

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <map>
#include <memory>
#include <mutex>
#include <set>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

using Clock = std::chrono::steady_clock;

// ---------------------------------------------------------------- framing
bool read_exact(int fd, void* buf, size_t n) {
  auto* p = static_cast<uint8_t*>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_exact(int fd, const void* buf, size_t n) {
  const auto* p = static_cast<const uint8_t*>(buf);
  while (n > 0) {
    ssize_t r = ::send(fd, p, n, MSG_NOSIGNAL);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool read_frame(int fd, std::vector<uint8_t>* out) {
  uint32_t len = 0;
  if (!read_exact(fd, &len, 4)) return false;
  out->resize(len);
  return len == 0 || read_exact(fd, out->data(), len);
}

bool write_frame(int fd, const std::vector<uint8_t>& payload) {
  uint32_t len = static_cast<uint32_t>(payload.size());
  if (!write_exact(fd, &len, 4)) return false;
  return payload.empty() || write_exact(fd, payload.data(), payload.size());
}

void put_u16(std::vector<uint8_t>* b, uint16_t v) {
  b->push_back(v & 0xff);
  b->push_back((v >> 8) & 0xff);
}

void put_u32(std::vector<uint8_t>* b, uint32_t v) {
  for (int i = 0; i < 4; ++i) b->push_back((v >> (8 * i)) & 0xff);
}

void put_str(std::vector<uint8_t>* b, const std::string& s) {
  put_u16(b, static_cast<uint16_t>(s.size()));
  b->insert(b->end(), s.begin(), s.end());
}

struct Reader {
  const uint8_t* p;
  const uint8_t* end;
  bool ok = true;

  uint16_t u16() {
    if (p + 2 > end) { ok = false; return 0; }
    uint16_t v = p[0] | (p[1] << 8);
    p += 2;
    return v;
  }
  uint32_t u32() {
    if (p + 4 > end) { ok = false; return 0; }
    uint32_t v = 0;
    for (int i = 0; i < 4; ++i) v |= static_cast<uint32_t>(p[i]) << (8 * i);
    p += 4;
    return v;
  }
  std::string str() {
    uint16_t n = u16();
    if (p + n > end) { ok = false; return ""; }
    std::string s(reinterpret_cast<const char*>(p), n);
    p += n;
    return s;
  }
};

// ----------------------------------------------------------------- server
struct PendingInfo {
  uint64_t order;            // announce sequence for deterministic ordering
  std::set<int> ready_ranks;
  int required = 0;          // ranks needed (0 = full world)
  Clock::time_point first_seen;
  bool warned = false;
  // Shape/dtype consistency: digest of the first announce, plus who
  // announced what when a divergence appears (for rank attribution).
  std::string digest;
  std::map<std::string, std::set<int>> by_digest;
  bool errored = false;
  // First announcer's group id, namespaced by their rank ("3:7"; "-1" for
  // ungrouped) — echoed to joined ranks so synthesized entries batch
  // exactly like the peers' grouped entries.
  std::string group = "-1";
  // Group STRUCTURE consistency: ids legitimately drift across ranks, but
  // grouped-vs-ungrouped divergence means ranks would batch differently at
  // the fusion threshold and execute mismatched programs — error instead.
  std::set<int> grouped_ranks;
  std::set<int> ungrouped_ranks;
  // Data dependency: -1 none, -2 needs every rank, >=0 needs that root.
  int data_dep = -1;
};

struct Server {
  int listen_fd = -1;
  int world = 0;
  // Per-rank sockets: fixed-size, preallocated before the loop thread
  // starts, written by run() and shutdown() by server_stop concurrently —
  // hence atomic slots rather than a resizable vector.
  std::unique_ptr<std::atomic<int>[]> fds;
  // Accepted-but-unidentified connection (rank handshake in flight); tracked
  // so server_stop can unblock a handshake read too.
  std::atomic<int> handshake_fd{-1};
  std::thread loop;
  std::atomic<bool> stop{false};
  // Held by run_inner() across a round's compute+write phase.  server_stop
  // acquires it (with a grace timeout) BEFORE severing client sockets, so a
  // shutdown initiated by rank 0 the instant its own response lands can
  // never cut off the same round's responses to the other ranks mid-write
  // (observed: rank 0 completes the final barrier and calls shutdown while
  // ranks 1..n-1's responses are still being written — they then die with
  // rc=-1 and a pending entry instead of completing).
  std::timed_mutex phase_mu;
  std::map<std::string, PendingInfo> pending;
  // Response cache (reference N8 response_cache.cc, re-derived for this
  // wire protocol): steady-state training announces the same
  // (name, digest, required, datadep) tuple every step; the server assigns
  // each tuple a compact uint32 id on first full announce and broadcasts
  // the assignment, after which clients send 4-byte cached announces (+
  // their per-step group tag) instead of the full strings.
  struct CacheRec {
    std::string name, digest, datadep;
    uint16_t required = 0;
  };
  // Bounded like the reference's capacity-limited cache, but without
  // eviction: digest-churning workloads (varying shapes/scales) simply
  // stop getting new ids past the cap and keep using full announces —
  // correct either way, and memory stays bounded on multi-day runs.
  static constexpr size_t kCacheCapacity = 65536;
  std::unordered_map<std::string, uint32_t> cache_keys;  // key -> id
  std::vector<CacheRec> cache_recs;                      // id -> record
  uint64_t announce_seq = 0;
  double stall_warn_s = 60.0;
  std::set<int> joined;
  int last_joined = -1;

  void run();
  void run_inner();
};

void Server::run() {
  run_inner();
  // Whatever ended the loop (peer death, accept failure, stop), surviving
  // clients must see EOF rather than hang in read_frame.  shutdown only —
  // close stays with server_stop after the join (fd-recycling discipline).
  for (int r = 0; r < world; ++r) {
    int fd = fds[r].load();
    if (fd >= 0) ::shutdown(fd, SHUT_RDWR);
  }
}

void Server::run_inner() {
  // Accept exactly `world` connections; first message from each client is a
  // 4-byte rank id.  All accepted fds land in `fds` (even on early stop) so
  // server_stop's cleanup owns closing them — run() never closes a
  // registered fd, which avoids shutdown() on a recycled fd number.
  for (int i = 0; i < world && !stop.load(); ++i) {
    int fd = ::accept(listen_fd, nullptr, nullptr);
    if (fd < 0) return;
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    handshake_fd.store(fd);
    if (stop.load()) {  // stop raced the accept; don't block in the read
      if (handshake_fd.exchange(-1) != -2) ::close(fd);
      return;
    }
    uint32_t rank = 0;
    bool ok = read_exact(fd, &rank, 4);
    // Ownership handoff: if server_stop already exchanged the slot to -2 it
    // owns shutdown() on this fd, so we must not close it (the number could
    // be recycled under its feet); we're stopping anyway.
    if (handshake_fd.exchange(-1) == -2) return;
    if (!ok || rank >= static_cast<uint32_t>(world) || fds[rank].load() >= 0) {
      ::close(fd);
      --i;
      continue;
    }
    fds[rank].store(fd);
  }
  for (int r = 0; r < world; ++r)
    if (fds[r].load() < 0) return;  // stopped before the world assembled

  std::vector<uint8_t> frame;
  while (!stop.load()) {
    // One lock-step round: a frame from every rank, then a reply to all.
    // Cache assignments created/confirmed this round, broadcast to all
    // ranks in the response (deduped; a client only adopts assignments
    // for names it announced itself).
    // value = the FULL cache key (name, digest, datadep, required) so a
    // client adopting the id can match it against exactly the tuple it
    // announced — two announces sharing (name, digest) but differing in
    // datadep/required (same tensor name under different process sets)
    // must not cross-adopt each other's ids.
    struct AssignRec {
      std::string name, digest, datadep;
      uint16_t required;
    };
    std::map<uint32_t, AssignRec> assigns;
    auto handle_announce = [&](int r, uint16_t required,
                               const std::string& name,
                               const std::string& digest,
                               const std::string& group,
                               const std::string& datadep) {
      auto it = pending.find(name);
      if (it == pending.end()) {
        PendingInfo info;
        info.order = announce_seq++;
        info.required = required ? required : world;
        info.first_seen = Clock::now();
        info.digest = digest;
        info.group = group == "-1" ? group : std::to_string(r) + ":" + group;
        info.data_dep = datadep.empty() ? -1 : std::atoi(datadep.c_str());
        it = pending.emplace(name, std::move(info)).first;
      }
      it->second.ready_ranks.insert(r);
      it->second.by_digest[digest].insert(r);
      (group == "-1" ? it->second.ungrouped_ranks
                     : it->second.grouped_ranks)
          .insert(r);
      if (digest != it->second.digest) {
        // Divergent submission (reference controller's consistency
        // check).  The message is rebuilt at response time so late
        // announcers still appear in the rank attribution.
        it->second.errored = true;
      }
    };
    for (int r = 0; r < world; ++r) {
      if (!read_frame(fds[r].load(), &frame)) { stop.store(true); break; }
      Reader rd{frame.data(), frame.data() + frame.size()};
      uint32_t n = rd.u32();
      for (uint32_t i = 0; i < n && rd.ok; ++i) {
        uint16_t required = rd.u16();
        std::string name = rd.str();
        std::string digest = rd.str();
        std::string group = rd.str();
        std::string datadep = rd.str();
        if (name == "\x1f__join__") {
          joined.insert(r);
          last_joined = r;
          continue;
        }
        // Assign (or confirm) the tuple's cache id so every announcer
        // eventually learns it and drops to the compact form.
        std::string key = name;
        key += '\x1f';
        key += digest;
        key += '\x1f';
        key += datadep;
        key += '\x1f';
        key += std::to_string(required);
        auto ck = cache_keys.find(key);
        if (ck == cache_keys.end() &&
            cache_recs.size() < kCacheCapacity) {
          uint32_t id = static_cast<uint32_t>(cache_recs.size());
          ck = cache_keys.emplace(key, id).first;
          cache_recs.push_back(CacheRec{name, digest, datadep, required});
        }
        if (ck != cache_keys.end())
          assigns[ck->second] = AssignRec{name, digest, datadep, required};
        handle_announce(r, required, name, digest, group, datadep);
      }
      // Optional compact section: cached announces (id + group tag).
      if (rd.ok && rd.p < rd.end) {
        uint32_t nc = rd.u32();
        for (uint32_t i = 0; i < nc && rd.ok; ++i) {
          uint32_t id = rd.u32();
          std::string group = rd.str();
          if (id < cache_recs.size()) {
            const CacheRec& rec = cache_recs[id];
            handle_announce(r, rec.required, rec.name, rec.digest, group,
                            rec.datadep);
          }
        }
      }
    }
    if (stop.load()) break;
    // Compute+write under phase_mu: see the field's comment.  Reads stay
    // outside the lock (they block on peers, and server_stop must be able
    // to sever a blocked read).
    std::lock_guard<std::timed_mutex> phase_lock(phase_mu);

    // Ready = reported by every rank (joined ranks count as implicitly
    // ready for world-level tensors); deterministic order by announce seq.
    // Errored tensors are never ready: their error is broadcast every round
    // until all required ranks have announced (so each has a local entry to
    // fail), then dropped.
    std::vector<std::tuple<uint64_t, std::string, std::string, std::string>>
        ready;
    std::vector<std::string> warns;
    std::vector<std::pair<std::string, std::string>> errs;
    auto now = Clock::now();
    for (auto it = pending.begin(); it != pending.end();) {
      auto& info = it->second;
      // Effective announce count: joined ranks are implicitly ready, but
      // only toward DEFAULT-process-set world tensors (wire names of other
      // sets carry a "\x1f" prefix the joined client cannot synthesize
      // for; join is a world-level operation in the reference too).
      bool world_level = info.required == world &&
                         it->first.find('\x1f') == std::string::npos;
      int have = static_cast<int>(info.ready_ranks.size());
      if (world_level) {
        for (int jr : joined)
          if (!info.ready_ranks.count(jr)) ++have;
      }
      // A collective that needs real data from a joined rank cannot be
      // satisfied with synthesized identity values: answer with a
      // per-tensor error instead of fabricating data (broadcast from a
      // joined root / allgather / alltoall — the reference errors here).
      if (!info.errored && world_level && !joined.empty() &&
          (info.data_dep == -2 ||
           (info.data_dep >= 0 && joined.count(info.data_dep)))) {
        std::string who;
        for (int jr : joined) {
          if (info.data_dep >= 0 && jr != info.data_dep) continue;
          if (!who.empty()) who += ",";
          who += std::to_string(jr);
        }
        errs.emplace_back(
            it->first, "tensor '" + it->first + "' requires data from " +
                           (info.data_dep >= 0 ? "root rank [" : "ranks [") +
                           who + "] which joined; collectives that need a "
                           "joined rank's data cannot run until all ranks "
                           "join");
        if (have >= info.required) {
          it = pending.erase(it);
          continue;
        }
        ++it;
        continue;
      }
      if (!info.grouped_ranks.empty() && !info.ungrouped_ranks.empty()) {
        // Grouped on some ranks, ungrouped on others: batching at the
        // fusion threshold would diverge → mismatched fused programs.
        std::string g, u;
        for (int rr : info.grouped_ranks) {
          if (!g.empty()) g += ",";
          g += std::to_string(rr);
        }
        for (int rr : info.ungrouped_ranks) {
          if (!u.empty()) u += ",";
          u += std::to_string(rr);
        }
        errs.emplace_back(
            it->first, "tensor '" + it->first +
                           "' negotiation failed: ranks [" + g +
                           "] submitted it as a GROUPED collective but "
                           "ranks [" + u + "] submitted it ungrouped");
        if (have >= info.required) {
          it = pending.erase(it);
          continue;
        }
        ++it;
        continue;
      }
      if (info.errored) {
        // Per-tensor error naming every rank on each side of the
        // divergence, rebuilt each round so late announcers are included.
        std::string msg = "tensor '" + it->first +
                          "' negotiation failed: mismatched submissions: ";
        bool first_d = true;
        for (auto& [d, ranks] : info.by_digest) {
          if (!first_d) msg += " vs ";
          first_d = false;
          std::string rs;
          for (int rr : ranks) {
            if (!rs.empty()) rs += ",";
            rs += std::to_string(rr);
          }
          msg += "ranks [" + rs + "] announced " + d;
        }
        errs.emplace_back(it->first, msg);
        if (have >= info.required) {
          it = pending.erase(it);
          continue;
        }
        ++it;
        continue;
      }
      if (have >= info.required) {
        ready.emplace_back(info.order, it->first, info.digest, info.group);
        it = pending.erase(it);
        continue;
      }
      double age =
          std::chrono::duration<double>(now - info.first_seen).count();
      if (age > stall_warn_s && !info.warned) {
        info.warned = true;
        std::string missing;
        for (int r = 0; r < world; ++r) {
          // Joined ranks are exempt only where they get implicit-ready
          // credit (world-level tensors); for subgroup tensors a joined
          // member really is the missing party — name it.
          if (!info.ready_ranks.count(r) &&
              !(world_level && joined.count(r))) {
            if (!missing.empty()) missing += ",";
            missing += std::to_string(r);
          }
        }
        warns.push_back("stall: tensor '" + it->first + "' waited " +
                        std::to_string(age) + "s; missing ranks [" + missing +
                        "]");
      }
      ++it;
    }
    std::sort(ready.begin(), ready.end());
    if (world > 0 && static_cast<int>(joined.size()) == world) {
      // Every rank joined: announce the epoch end (digest = last joiner)
      // and reset so the world can resume normal collectives.
      ready.emplace_back(UINT64_MAX, "\x1f__all_joined__",
                         std::to_string(last_joined), "-1");
      joined.clear();
      last_joined = -1;
    }

    std::vector<uint8_t> resp;
    put_u32(&resp, static_cast<uint32_t>(ready.size()));
    for (auto& [ord, name, digest, group] : ready) {
      put_str(&resp, name);
      put_str(&resp, digest);
      put_str(&resp, group);
    }
    put_u32(&resp, static_cast<uint32_t>(warns.size()));
    for (auto& w : warns) put_str(&resp, w);
    put_u32(&resp, static_cast<uint32_t>(errs.size()));
    for (auto& [name, msg] : errs) {
      put_str(&resp, name);
      put_str(&resp, msg);
    }
    put_u32(&resp, static_cast<uint32_t>(assigns.size()));
    for (auto& [id, rec] : assigns) {
      put_str(&resp, rec.name);
      put_str(&resp, rec.digest);
      put_str(&resp, rec.datadep);
      put_u16(&resp, rec.required);
      put_u32(&resp, id);
    }
    // Attempt EVERY rank before honoring a failure: one dead/closing peer
    // must not cut the survivors off from a round's computed verdicts
    // (they may contain the ready broadcast that lets them finish cleanly).
    bool write_failed = false;
    for (int r = 0; r < world; ++r) {
      if (!write_frame(fds[r].load(), resp)) write_failed = true;
    }
    if (write_failed) stop.store(true);
  }
  // fds are closed by hvdtpu_server_stop after the thread joins.
}

struct Client {
  int fd = -1;
};

}  // namespace

extern "C" {

void* hvdtpu_server_start(int port, int world, double stall_warn_s) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  ::setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0 ||
      ::listen(fd, world) < 0) {
    ::close(fd);
    return nullptr;
  }
  auto* s = new Server();
  s->listen_fd = fd;
  s->world = world;
  s->stall_warn_s = stall_warn_s;
  s->fds = std::make_unique<std::atomic<int>[]>(world);
  for (int i = 0; i < world; ++i) s->fds[i].store(-1);
  s->loop = std::thread([s] { s->run(); });
  return s;
}

void hvdtpu_server_stop(void* handle) {
  auto* s = static_cast<Server*>(handle);
  if (!s) return;
  // shutdown (not close) unblocks the loop thread's blocking accept/recv;
  // actual closes happen only after the join so no fd is closed (and
  // potentially recycled) while the loop might still read it.
  s->stop.store(true);
  ::shutdown(s->listen_fd, SHUT_RDWR);
  int hs = s->handshake_fd.exchange(-2);
  if (hs >= 0) ::shutdown(hs, SHUT_RDWR);
  // Let an in-flight round finish broadcasting its responses before
  // severing the sockets (phase_mu comment): without this, peers whose
  // response for the CURRENT round had not been written yet fail their
  // round with a pending entry.  Timed: a peer wedged enough to block a
  // small write for 5s is a dead peer; proceed and sever.
  bool locked = s->phase_mu.try_lock_for(std::chrono::seconds(5));
  for (int i = 0; i < s->world; ++i) {
    int fd = s->fds[i].load();
    if (fd >= 0) ::shutdown(fd, SHUT_RDWR);
  }
  if (locked) s->phase_mu.unlock();
  if (s->loop.joinable()) s->loop.join();
  // If we took ownership of a mid-handshake fd (exchanged to -2 above),
  // run() deliberately did not close it — close it now, after the join.
  if (hs >= 0) ::close(hs);
  ::close(s->listen_fd);
  for (int i = 0; i < s->world; ++i) {
    int fd = s->fds[i].load();
    if (fd >= 0) ::close(fd);
  }
  delete s;
}

void* hvdtpu_client_connect(const char* host, int port, int rank,
                            int timeout_ms) {
  auto deadline = Clock::now() + std::chrono::milliseconds(timeout_ms);
  std::string port_str = std::to_string(port);
  while (Clock::now() < deadline) {
    // Resolve every attempt (DNS, not just dotted IPv4 — hostnames from
    // `-H node1:2,...` must work; resolution can also succeed late while
    // hosts boot).
    addrinfo hints{};
    hints.ai_family = AF_UNSPEC;
    hints.ai_socktype = SOCK_STREAM;
    addrinfo* res = nullptr;
    if (::getaddrinfo(host, port_str.c_str(), &hints, &res) != 0) {
      std::this_thread::sleep_for(std::chrono::milliseconds(200));
      continue;
    }
    for (addrinfo* ai = res; ai; ai = ai->ai_next) {
      int fd = ::socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
      if (fd < 0) continue;
      if (::connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) {
        int one = 1;
        ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
        uint32_t r = static_cast<uint32_t>(rank);
        if (!write_exact(fd, &r, 4)) {
          ::close(fd);
          break;  // retry from scratch
        }
        ::freeaddrinfo(res);
        auto* c = new Client();
        c->fd = fd;
        return c;
      }
      ::close(fd);
    }
    ::freeaddrinfo(res);
    std::this_thread::sleep_for(std::chrono::milliseconds(50));
  }
  return nullptr;
}

// One lock-step round: send req frame, block for response frame.
// Returns response length, 0 on empty response, -1 on error, -2 if the
// response exceeds resp_cap.
int hvdtpu_client_round(void* handle, const uint8_t* req, int req_len,
                        uint8_t* resp_buf, int resp_cap) {
  auto* c = static_cast<Client*>(handle);
  if (!c || c->fd < 0) return -1;
  std::vector<uint8_t> payload(req, req + req_len);
  if (!write_frame(c->fd, payload)) return -1;
  std::vector<uint8_t> resp;
  if (!read_frame(c->fd, &resp)) return -1;
  if (static_cast<int>(resp.size()) > resp_cap) return -2;
  if (!resp.empty()) std::memcpy(resp_buf, resp.data(), resp.size());
  return static_cast<int>(resp.size());
}

// Unblock a thread stuck in hvdtpu_client_round (recv returns 0 after the
// socket shutdown) WITHOUT freeing the Client — call before client_close so
// shutdown ordering can't use-after-free a blocked round.
void hvdtpu_client_interrupt(void* handle) {
  auto* c = static_cast<Client*>(handle);
  if (c && c->fd >= 0) ::shutdown(c->fd, SHUT_RDWR);
}

void hvdtpu_client_close(void* handle) {
  auto* c = static_cast<Client*>(handle);
  if (!c) return;
  if (c->fd >= 0) ::close(c->fd);
  delete c;
}

}  // extern "C"
