// TPU-native coordinator control plane: TCP negotiation over DCN.
//
// Native equivalent of the reference's controller transports
// (horovod/common/mpi/mpi_controller.cc, horovod/common/gloo/gloo_controller.cc
// — SURVEY.md §2a N2/N3/N4) with the transport swapped per SURVEY.md §5
// ("distributed communication backend"): instead of MPI gather/bcast of
// serialized Request/Response messages, a rank-0 TCP server runs lock-step
// negotiation rounds with every worker over DCN.  The data plane is NOT
// here — fused collectives execute as XLA programs over ICI; this is purely
// the out-of-graph readiness protocol (which tensors are pending on every
// rank, in what order), plus rank-0 stall tracking (N11's role).
//
// Wire protocol (all little-endian, length-prefixed frames):
//   frame  := uint32 payload_len, payload
//   C->S   := uint32 n_announce, n_announce * { uint16 required,
//                                               uint16 len, bytes name,
//                                               uint16 dlen, bytes digest,
//                                               uint16 glen, bytes group,
//                                               uint16 plen, bytes datadep,
//                                               uint16 tlen, bytes tag }
//             uint32 bv_len, bytes bitvec       (bit i = cache slot i pending)
//             uint32 n_tag, n_tag * { uint32 slot, uint16 len, bytes tag }
//             [optional, protocol v3] uint32 magic "MON1",
//                                     uint32 blen, bytes monitor_blob
//             (the monitor side-channel: an opaque telemetry snapshot the
//              rank ships at its HOROVOD_MONITOR_INTERVAL — absent on most
//              rounds.  A pre-v3 server never parses past the tag section,
//              so the trailing bytes are ignored: old servers tolerate new
//              clients.  Low priority by construction: the blob rides the
//              same lock-step frame, so it can never delay a negotiation
//              verdict — it only adds bytes to rounds that carry it)
//             (the bitvector is the steady-state fast path: a slot id is a
//              replicated handle for a (name, digest, required, datadep,
//              grouped) tuple the server assigned on its first full
//              announce; a round in the warm regime carries ONLY the
//              fixed-size bitvector — no per-tensor metadata.  `tag` is the
//              runtime sanitizer's seq/call-site tag: on the full path it
//              used to ride inside the digest, now it travels beside it so
//              the slot key stays step-invariant while divergence detection
//              keeps working on the cached path via the sparse tag section)
//             (names newly enqueued on this rank since the last round;
//              `required` = number of ranks that must announce before the
//              tensor is ready — process-set size; 0 means the full world.
//              `digest` describes the submission — op|dtype|shape|root —
//              so rank 0 can reject divergent submissions (the reference
//              controller's shape/dtype consistency checks, SURVEY.md N2).
//              `group` is the announcer's local grouped-collective id ("-1"
//              for ungrouped) — NOT part of the mismatch comparison, since
//              group counters legitimately drift across ranks (uneven join
//              epochs); the server namespaces it by first-announcer rank
//              and echoes it so joined ranks preserve group batching.
//              `datadep` marks collectives that need real data from
//              specific ranks: "-1" none (reductions), "-2" every rank
//              (allgather/alltoall), or a root rank (broadcast) — if the
//              needed rank has JOINED the server answers with a per-tensor
//              error instead of fabricating data.
//              A round with nothing new sends n_announce = 0)
//   S->C   := uint32 n_ready,   n_ready * { uint16 len, bytes name,
//                                           uint16 dlen, bytes digest,
//                                           uint16 glen, bytes group }
//             uint32 n_warn,    n_warn  * { uint16 len, bytes text }
//             uint32 n_err,     n_err   * { uint16 len, bytes name,
//                                           uint16 mlen, bytes message }
//             uint32 n_assign,  n_assign * { name, digest, datadep,
//                                            uint16 required,
//                                            uint16 grouped, uint32 id }
//             uint32 bv_len, bytes ready_bitvec (bit i = slot i ready; only
//                                                used while no rank is
//                                                joined — joined ranks need
//                                                the digest strings to
//                                                synthesize contributions)
//             uint32 n_evict, n_evict * uint32 slot
//             [protocol v3] uint32 magic "MON1", uint32 n_blob,
//                           n_blob * { uint32 rank, uint32 blen, bytes }
//             (store-and-forward of the monitor blobs received THIS round,
//              re-broadcast to every rank so each process — most usefully
//              rank 0's HTTP exporter — can hold the fleet-wide telemetry
//              table.  Always appended (even empty): the magic doubles as
//              the server's protocol-v3 capability advertisement, which is
//              how clients version-gate their own monitor frames.  Pre-v3
//              clients stop parsing after the eviction section and ignore
//              the trailing bytes)
//             [protocol v4, FIRST ROUND ONLY] uint32 magic "FLT1",
//                           uint32 0
//             (the server's fault-tolerance capability advertisement.
//              Appended only to round 1's response so the warm path pays
//              ZERO extra bytes — by round 2 every client has latched it.
//              Symmetrically, a v4 client appends an empty FLT1 section to
//              its FIRST request only; the server latches the rank as
//              v4-capable and may send it the typed ABORT frame below.
//              Trailing sections in both directions are (magic, len,
//              payload) tuples walked generically, so MON1 and FLT1
//              compose in any order and unknown magics are skipped — the
//              same old-peers-ignore-trailing-bytes contract as MON1)
//
//   [protocol v5, FIRST ROUND ONLY] uint32 magic "AGG5", uint32 0
//             (the hierarchical-control-plane capability advertisement,
//              both directions, round 1 only — exactly the FLT1 pattern,
//              so the warm path carries zero extra bytes.  On the request
//              side it rides BEFORE the FLT1 section: the server's
//              pre-processing FLT1 salvage reads the frame's final 8
//              bytes, so FLT1 must stay last.)
//
//   LEAVE  := uint32 0xFFFFFFFE, uint32 magic "LVE6"
//             (protocol v6 clean departure: a rank announces its own
//              orderly exit IN PLACE of a round frame, immediately before
//              severing its socket.  0xFFFFFFFE is an impossible
//              n_announce, so the frame is unambiguous against every
//              normal request.  The server drops the rank from the gather
//              with NO dead-peer verdict: the rank stops counting toward
//              world-level readiness (pending entries keep their raw
//              required=0 marker and re-materialize against the shrunk
//              effective world at verdict time), its connection leaves the
//              poller, and survivors are told through a trailing LVE6
//              response section.  The ONE abort case: the leaver still has
//              outstanding negotiated work (a pending tensor it announced,
//              or — while joined — an implicit world-level credit) whose
//              readiness would include a rank that will never execute it;
//              then the server broadcasts the typed ABORT naming the
//              leaver, exactly like a crash, because the departure was NOT
//              clean.  Version gating: the client advertises v6 with a
//              round-1 LVE6 request section (between AGG5 and the final
//              FLT1) and the server advertises with a round-1 LVE6
//              response section (after AGG5); the server honors a LEAVE
//              only when EVERY survivor has latched v6 — a pre-v6 survivor
//              cannot parse the leave notice and would execute
//              shrunk-world verdicts its fixed-size data plane cannot
//              resolve — otherwise the LEAVE is ignored and the leaver's
//              subsequent socket sever produces the legacy v4 verdict.
//              Races: a LEAVE landing mid-gather counts as the rank's
//              round frame (the deadline is satisfied, the gather
//              completes with the survivors); one landing during a
//              response write sits in the reassembly buffer and is taken
//              as the NEXT round's frame — the sock_dead the sever leaves
//              behind is ignored for a left connection, never a verdict.)
//
//   S->C   += [protocol v6] uint32 magic "LVE6", uint32 len,
//             uint32 n_left, n_left * uint32 rank
//             (ranks that left THIS round, appended after the MON1
//              section only on rounds where someone actually left — the
//              warm path carries zero extra bytes — plus an empty
//              (n_left = 0) section on round 1 as the capability ad.
//              Pre-v6 clients stop their trailing walk at the unknown
//              magic and lose nothing.)
//
//   [protocol v7, zero-RTT warm path] uint32 magic "ZRT7"
//             Speculative readiness: when a cache slot has been
//             ready-on-first-announce for spec_ready_after consecutive
//             rounds (hvdtpu_server_start's 6th arg; 0 = off), the server
//             piggybacks a PREDICTED next-round ready verdict on this
//             round's response:
//               S->C   += uint32 "ZRT7", uint32 len,
//                         uint32 n_pred, n_pred * uint32 slot
//             (appended only on rounds that actually predict — the warm
//              path with speculation off carries zero extra bytes — plus
//              an empty (n_pred = 0) section on round 1 as the capability
//              ad, after the LVE6 ad so pre-v7 clients latch everything
//              older before their trailing walk stops.)  A client whose
//              ENTIRE next-round announce is exactly the predicted slot
//              set may then dispatch the verdict without waiting for the
//              response: it sends the round frame with a one-byte confirm
//              section appended —
//               C->S   += uint32 "ZRT7", uint32 1, uint8 1
//              — and defers reading the response to the start of its next
//              round (the zero-RTT skip; the v4 abort and LVE6 notices a
//              deferred response may carry are honored there, one round
//              late, bounded by the client's in-flight window).  The
//              request-side ad is an empty ZRT7 section on round 1,
//              between LVE6 and the final FLT1.  Predictions are only
//              emitted while EVERY rank has latched v7 (no wire bytes
//              change for old peers), no rank is joined, and no rank left
//              this round.  A mispredict (a predicted slot not ready next
//              round — a rank skipped a cycle, or any slot-invalidation
//              event: digest change, eviction, join epoch, LEAVE) resets
//              the slot's streak, so speculation disengages and the
//              verdict resolves through normal full rounds until the
//              streak rebuilds; the speculating client merely consumed a
//              verdict early — its announce stays pending server-side and
//              the late real verdict is absorbed by its next entry, so
//              results stay bitwise identical.
//
//   AGENT  := a per-host aggregator (horovod_tpu/common/host_agent.py) may
//             connect IN PLACE of its host's ranks: handshake word
//             0xFFFFFF05 ("v5 agent hello", outside the rank space), then
//             one frame { u32 host_index, u32 n_ranks, n_ranks * u32 rank }
//             claiming the ranks it serves.  Each round the agent sends ONE
//             uplink frame for the whole host:
//
//   uplink := u32 magic "HUP5"
//             u32 n_dead, n_dead * u32 rank      (local ranks whose socket
//                                                 died — propagated up so
//                                                 the root can abort with
//                                                 rank attribution)
//             u32 agg_nranks                     (0 = no aggregate section)
//             [if agg_nranks>0] u32 bv_len, bytes bitvec
//             u32 n_sub, n_sub * { u32 rank, u32 flen, bytes rank-frame }
//             u32 n_mon, n_mon * { u32 rank, u32 blen, bytes blob }
//
//             (the aggregate bitvector is the warm-path win: when every
//              local rank's round frame is a pure warm frame — no full
//              announces, no tags, no trailing sections — with an
//              IDENTICAL pending bitvector (the synchronized steady state:
//              all ranks submit the same tensors in the same cycle), the
//              agent collapses them into ONE fixed-size section that
//              counts for all agg_nranks ranks at once.  Any asymmetric or
//              non-warm frame is forwarded per-rank in the sub section,
//              byte-identical to what the rank sent (minus extracted MON1
//              blobs, which travel deduplicated in the mon section), so
//              full negotiation, sanitizer tags, FLT1 ads and join frames
//              keep their exact flat-mode semantics.  The root answers
//              with its ordinary response frame, written ONCE per host;
//              the agent fans it down verbatim — responses were already
//              rank-agnostic.  Root-side gather work therefore scales
//              with hosts, not ranks: one readable fd, one frame parse
//              and one response write per host per round.)
//
//   ABORT  := uint32 0xFFFFFFFF, uint32 magic "ABT4",
//             uint32 n_dead, n_dead * uint32 rank, { u16 len, reason }
//             (protocol v4 liveness verdict, sent IN PLACE of a normal
//              response when the server declares ranks dead — a client
//              socket died (recv 0 / ECONNRESET / write failure) or a
//              rank missed the per-round deadline.  0xFFFFFFFF is an
//              impossible n_ready, so v4 clients detect the frame
//              unambiguously and raise a typed PeerFailureError carrying
//              the dead-rank list; v3 clients never receive it — the
//              server version-gates on the request-side FLT1 ad and
//              simply severs pre-v4 clients (they fail with the legacy
//              rc=-1 path, exactly the pre-v4 behavior).  The server
//              stops after an abort: the surviving world re-forms through
//              the elastic driver, never through a half-dead server)
//             (evictions are broadcast in the same lock-step round on every
//              rank, so client slot tables can never diverge; a join epoch
//              flushes ALL slots — full renegotiation while the world is
//              uneven, and fresh slot state afterwards)
//             (ready = pending on ALL ranks, in deterministic order:
//              first-announce round, then name; the digest rides along so
//              JOINED ranks can synthesize zero contributions for tensors
//              they never submitted — the reference's hvd.join() semantics;
//              warn = stall diagnoses naming the missing ranks, the
//              reference's stall_inspector output; err = per-tensor
//              negotiation failures — digest mismatch across ranks —
//              broadcast until every required rank has announced the name,
//              the reference's per-tensor error Response)
//
// join protocol: announcing the reserved name "\x1f__join__" marks the
// sender joined (reference: hvd.join, horovod/common/controller.cc's join
// handling).  Joined ranks count as implicitly ready for every world-level
// tensor.  When ALL ranks have joined, the server broadcasts the reserved
// ready entry "\x1f__all_joined__" whose digest is the last joining rank,
// then resets join state (the world resumes normal operation).
//
// Exported C ABI (ctypes-consumed by horovod_tpu/common/native.py):
//   hvdtpu_server_start(port, world, stall_warn_s, cache_capacity,
//                       round_deadline_ms, spec_ready_after,
//                       spec_seed) -> handle
//       (spec_seed: initial speculation streak for newly created cache
//        slots — the elastic streak-carryover hint a re-rendezvous
//        survivor passes so warm speculation re-engages in O(1) rounds;
//        0 = relearn from zero, the non-elastic default)
//   hvdtpu_server_stop(handle)
//   hvdtpu_client_connect(host, port, rank, timeout_ms) -> handle
//   hvdtpu_client_round(handle, req, req_len, resp_buf, resp_cap) -> resp_len
//   hvdtpu_client_send(handle, req, req_len) -> 0 / -1
//   hvdtpu_client_recv(handle, resp_buf, resp_cap, timeout_ms)
//       -> resp_len / -1 (error) / -2 (overflow) / -3 (timeout)
//   hvdtpu_client_pending(handle) -> 1 if a frame is already readable
//   hvdtpu_client_close(handle)

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#ifdef __linux__
#include <sys/epoll.h>
#endif
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <cerrno>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <map>
#include <memory>
#include <mutex>
#include <set>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

using Clock = std::chrono::steady_clock;

// Monitor side-channel section marker ("MON1" little-endian).  Doubles as
// the protocol-v3 capability advertisement in responses.
constexpr uint32_t kMonMagic = 0x314e4f4d;
// Fault-tolerance capability section marker ("FLT1" little-endian) —
// protocol v4.  Rides a trailing (magic, len) section exactly like MON1:
// request side on round 1 only (client ad), response side on round 1 only
// (server ad), so the warm path carries zero extra bytes.
constexpr uint32_t kFltMagic = 0x31544c46;
// Typed abort frame marker ("ABT4") behind the 0xFFFFFFFF escape.
constexpr uint32_t kAbortMagic = 0x34544241;
constexpr uint32_t kAbortEscape = 0xffffffffu;
// Hierarchical control plane (protocol v5): capability ad ("AGG5", round 1
// only in both directions, exactly the FLT1 pattern), the per-host agent's
// hello word (outside the rank space — ranks are < world < 2^31), and the
// host uplink frame magic ("HUP5").
constexpr uint32_t kAggMagic = 0x35474741;
constexpr uint32_t kAgentHello = 0xffffff05u;
constexpr uint32_t kHupMagic = 0x35505548;
// Clean-LEAVE (protocol v6): the request-side escape word (an impossible
// n_announce, mirroring the response side's 0xFFFFFFFF abort escape) and
// the "LVE6" magic that doubles as the capability ad in both directions.
constexpr uint32_t kLeaveEscape = 0xfffffffeu;
constexpr uint32_t kLeaveMagic = 0x3645564c;
// Zero-RTT warm path (protocol v7): "ZRT7" doubles as the round-1
// capability ad (both directions), the response-side prediction section
// marker, and the request-side one-byte speculation confirm.
constexpr uint32_t kZrtMagic = 0x3754525a;

// A standalone clean-LEAVE frame: { kLeaveEscape, kLeaveMagic }.
bool is_leave_frame(const uint8_t* p, size_t n) {
  if (n < 8) return false;
  uint32_t esc = 0, magic = 0;
  std::memcpy(&esc, p, 4);
  std::memcpy(&magic, p + 4, 4);
  return esc == kLeaveEscape && magic == kLeaveMagic;
}
// Per-blob and per-response caps for the monitor section: the aggregate
// re-broadcast must stay well inside the client's fixed 4MB receive
// buffer (_RESP_CAP in common/controller.py) no matter how many ranks
// report in one round — telemetry that overflows is dropped, never a
// negotiation failure.  Dropped blobs are naturally retried: the rank
// re-reports at its next interval.
constexpr uint32_t kMonBlobCap = 64 * 1024;
constexpr size_t kMonSectionCap = 1024 * 1024;

// ---------------------------------------------------------------- framing
bool read_exact(int fd, void* buf, size_t n) {
  auto* p = static_cast<uint8_t*>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_exact(int fd, const void* buf, size_t n) {
  const auto* p = static_cast<const uint8_t*>(buf);
  while (n > 0) {
    ssize_t r = ::send(fd, p, n, MSG_NOSIGNAL);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool read_frame(int fd, std::vector<uint8_t>* out) {
  uint32_t len = 0;
  if (!read_exact(fd, &len, 4)) return false;
  out->resize(len);
  return len == 0 || read_exact(fd, out->data(), len);
}

// Deadline-bounded read: like read_exact, but every recv is gated on a
// poll() against an ABSOLUTE deadline, so a peer that wedges mid-frame-
// write (SIGSTOPped / paged out after the length prefix) cannot block
// the caller past its deadline — a blocking read here would defeat both
// the server's per-round deadline and the client's round timeout.
// Returns 1 on success, 0 on deadline expiry, -1 on a dead socket (or
// `stop`, polled each quantum so teardown never waits the deadline out).
int read_exact_deadline(int fd, void* buf, size_t n,
                        Clock::time_point deadline,
                        const std::atomic<bool>* stop = nullptr) {
  auto* p = static_cast<uint8_t*>(buf);
  while (n > 0) {
    auto rem = std::chrono::duration_cast<std::chrono::milliseconds>(
                   deadline - Clock::now())
                   .count();
    if (rem <= 0) return 0;
    if (stop != nullptr && stop->load()) return -1;
    pollfd pfd{fd, POLLIN, 0};
    int pn = ::poll(&pfd, 1, static_cast<int>(std::min<int64_t>(rem, 100)));
    if (pn < 0) {
      if (errno == EINTR) continue;
      return -1;
    }
    if (pn == 0) continue;
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return -1;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return 1;
}

int read_frame_deadline(int fd, std::vector<uint8_t>* out,
                        Clock::time_point deadline,
                        const std::atomic<bool>* stop = nullptr) {
  uint32_t len = 0;
  int rc = read_exact_deadline(fd, &len, 4, deadline, stop);
  if (rc <= 0) return rc;
  out->resize(len);
  if (len == 0) return 1;
  return read_exact_deadline(fd, out->data(), len, deadline, stop);
}

bool write_frame(int fd, const std::vector<uint8_t>& payload) {
  uint32_t len = static_cast<uint32_t>(payload.size());
  if (!write_exact(fd, &len, 4)) return false;
  return payload.empty() || write_exact(fd, payload.data(), payload.size());
}

void put_u16(std::vector<uint8_t>* b, uint16_t v) {
  b->push_back(v & 0xff);
  b->push_back((v >> 8) & 0xff);
}

void put_u32(std::vector<uint8_t>* b, uint32_t v) {
  for (int i = 0; i < 4; ++i) b->push_back((v >> (8 * i)) & 0xff);
}

void put_str(std::vector<uint8_t>* b, const std::string& s) {
  put_u16(b, static_cast<uint16_t>(s.size()));
  b->insert(b->end(), s.begin(), s.end());
}

struct Reader {
  const uint8_t* p;
  const uint8_t* end;
  bool ok = true;

  uint16_t u16() {
    if (p + 2 > end) { ok = false; return 0; }
    uint16_t v = p[0] | (p[1] << 8);
    p += 2;
    return v;
  }
  uint32_t u32() {
    if (p + 4 > end) { ok = false; return 0; }
    uint32_t v = 0;
    for (int i = 0; i < 4; ++i) v |= static_cast<uint32_t>(p[i]) << (8 * i);
    p += 4;
    return v;
  }
  std::string str() {
    uint16_t n = u16();
    if (p + n > end) { ok = false; return ""; }
    std::string s(reinterpret_cast<const char*>(p), n);
    p += n;
    return s;
  }
};

// ------------------------------------------------------- connection state
// One accepted control-plane connection: a single rank (flat mode) or a
// per-host agent speaking for several ranks (protocol v5).  Reads are
// non-blocking (MSG_DONTWAIT; the fd itself stays blocking so response
// writes need no EAGAIN handling) into a per-connection reassembly buffer:
// the gather loop never blocks inside one peer's half-written frame, so a
// wedged peer can only cost its own round-deadline verdict, never the
// whole control plane's liveness.
struct Conn {
  int fd = -1;
  std::vector<int> ranks;           // ranks this connection speaks for
  bool is_agent = false;
  std::vector<uint8_t> inbuf;       // partial frame bytes (reassembly)
  std::vector<std::vector<uint8_t>> frames;  // complete frames, FIFO
  bool sock_dead = false;
  // Every rank this connection spoke for departed via clean LEAVE
  // (protocol v6): removed from the poller, skipped by the gather, the
  // deadline verdicts and the response write — its inevitable trailing
  // EOF must never become a dead-peer verdict.  (An agent connection
  // only flips this once its LAST local rank left; individual leaves
  // just shrink `ranks`.)
  bool left = false;

  // Drain everything currently readable without blocking; extract complete
  // frames.  Returns false once the socket is dead (EOF / hard error).
  int dead_errno = 0;   // diagnostic: errno at death (0 = orderly EOF)
  bool drain() {
    if (sock_dead) return false;
    uint8_t tmp[65536];
    for (;;) {
      ssize_t r = ::recv(fd, tmp, sizeof(tmp), MSG_DONTWAIT);
      if (r > 0) {
        inbuf.insert(inbuf.end(), tmp, tmp + r);
        if (static_cast<size_t>(r) < sizeof(tmp)) break;  // likely drained
        continue;
      }
      if (r == 0) { sock_dead = true; dead_errno = 0; break; }
      if (errno == EAGAIN || errno == EWOULDBLOCK) break;
      if (errno == EINTR) continue;
      sock_dead = true;
      dead_errno = errno;
      break;
    }
    // Reassemble: length-prefixed frames, possibly several per drain.
    while (inbuf.size() >= 4) {
      uint32_t len = inbuf[0] | (inbuf[1] << 8) | (inbuf[2] << 16)
          | (static_cast<uint32_t>(inbuf[3]) << 24);
      if (inbuf.size() < 4 + static_cast<size_t>(len)) break;
      frames.emplace_back(inbuf.begin() + 4, inbuf.begin() + 4 + len);
      inbuf.erase(inbuf.begin(), inbuf.begin() + 4 + len);
    }
    return !sock_dead;
  }
};

// Readiness multiplexer for the gather loop: epoll on Linux, a pollfd-set
// fallback elsewhere (or under HVD_TPU_COORD_EPOLL=0, which keeps the
// fallback testable on Linux).  One instance per server lifetime — fds are
// registered once after the world assembles, not rebuilt per round like
// the old poll-per-fd gather.
class Poller {
 public:
  Poller() {
#ifdef __linux__
    const char* env = std::getenv("HVD_TPU_COORD_EPOLL");
    if (env == nullptr || env[0] != '0') epfd_ = ::epoll_create1(0);
#endif
  }
  ~Poller() {
#ifdef __linux__
    if (epfd_ >= 0) ::close(epfd_);
#endif
  }
  bool using_epoll() const { return epfd_ >= 0; }
  void add(int fd, int idx) {
#ifdef __linux__
    if (epfd_ >= 0) {
      epoll_event ev{};
      ev.events = EPOLLIN;
      ev.data.u32 = static_cast<uint32_t>(idx);
      ::epoll_ctl(epfd_, EPOLL_CTL_ADD, fd, &ev);
      return;
    }
#endif
    pfds_.push_back(pollfd{fd, POLLIN, 0});
    idxs_.push_back(idx);
  }
  void remove(int fd) {
#ifdef __linux__
    if (epfd_ >= 0) {
      ::epoll_ctl(epfd_, EPOLL_CTL_DEL, fd, nullptr);
      return;
    }
#endif
    for (size_t i = 0; i < pfds_.size(); ++i)
      if (pfds_[i].fd == fd) {
        pfds_.erase(pfds_.begin() + i);
        idxs_.erase(idxs_.begin() + i);
        break;
      }
  }
  // Fills `ready` with registered indices that have data (or EOF/error)
  // pending.  Returns poll()/epoll_wait() rc (<0 only on a real error).
  int wait(int timeout_ms, std::vector<int>* ready) {
    ready->clear();
#ifdef __linux__
    if (epfd_ >= 0) {
      epoll_event evs[64];
      int n = ::epoll_wait(epfd_, evs, 64, timeout_ms);
      for (int i = 0; i < n; ++i)
        ready->push_back(static_cast<int>(evs[i].data.u32));
      return n;
    }
#endif
    int n = ::poll(pfds_.data(), static_cast<nfds_t>(pfds_.size()),
                   timeout_ms);
    if (n > 0)
      for (size_t i = 0; i < pfds_.size(); ++i)
        if (pfds_[i].revents & (POLLIN | POLLHUP | POLLERR))
          ready->push_back(idxs_[i]);
    return n;
  }

 private:
  int epfd_ = -1;
  std::vector<pollfd> pfds_;   // fallback set
  std::vector<int> idxs_;
};

// ----------------------------------------------------------------- server
struct PendingInfo {
  uint64_t order;            // announce sequence for deterministic ordering
  std::set<int> ready_ranks;
  // Ranks needed.  Kept RAW (0 = the full world, the announce-side
  // marker) and materialized against the EFFECTIVE world — world minus
  // clean leavers — at verdict time, so a rank departing via LEAVE
  // (protocol v6) shrinks the threshold of already-pending world-level
  // tensors instead of wedging them on a contribution that will never
  // come.  Sub-process-set thresholds (required > 0) are unaffected.
  int required = 0;
  Clock::time_point first_seen;
  bool warned = false;
  // Shape/dtype consistency: digest of the first announce, plus who
  // announced what when a divergence appears (for rank attribution).
  std::string digest;
  std::map<std::string, std::set<int>> by_digest;
  bool errored = false;
  // Cache slot this pending instance may be answered through (-1 = must use
  // the string path: no slot exists, a full announcer could not be assigned
  // one, or a join epoch flushed the table mid-negotiation).
  int64_t slot = INT64_MIN;  // INT64_MIN = unset
  // First announcer's group id, namespaced by their rank ("3:7"; "-1" for
  // ungrouped) — echoed to joined ranks so synthesized entries batch
  // exactly like the peers' grouped entries.
  std::string group = "-1";
  // Group STRUCTURE consistency: ids legitimately drift across ranks, but
  // grouped-vs-ungrouped divergence means ranks would batch differently at
  // the fusion threshold and execute mismatched programs — error instead.
  std::set<int> grouped_ranks;
  std::set<int> ungrouped_ranks;
  // Data dependency: -1 none, -2 needs every rank, >=0 needs that root.
  int data_dep = -1;
  // Round this pending instance was created in: a slot verdict counts
  // toward its speculation streak (protocol v7) only when announce and
  // ready landed in the SAME round — the warm steady-state shape.
  uint64_t round_created = 0;
};

struct Server {
  int listen_fd = -1;
  int world = 0;
  // Per-rank sockets: fixed-size, preallocated before the loop thread
  // starts, written by run() and shutdown() by server_stop concurrently —
  // hence atomic slots rather than a resizable vector.
  std::unique_ptr<std::atomic<int>[]> fds;
  // Accepted-but-unidentified connection (rank handshake in flight); tracked
  // so server_stop can unblock a handshake read too.
  std::atomic<int> handshake_fd{-1};
  std::thread loop;
  std::atomic<bool> stop{false};
  // Held by run_inner() across a round's compute+write phase.  server_stop
  // acquires it (with a grace timeout) BEFORE severing client sockets, so a
  // shutdown initiated by rank 0 the instant its own response lands can
  // never cut off the same round's responses to the other ranks mid-write
  // (observed: rank 0 completes the final barrier and calls shutdown while
  // ranks 1..n-1's responses are still being written — they then die with
  // rc=-1 and a pending entry instead of completing).
  std::timed_mutex phase_mu;
  std::map<std::string, PendingInfo> pending;
  // Response cache (reference N8 response_cache.cc, re-derived for this
  // wire protocol): steady-state training announces the same
  // (name, digest, required, datadep, grouped) tuple every step; the server
  // assigns each tuple a compact uint32 slot on first full announce and
  // broadcasts the assignment, after which clients announce via a single
  // fixed-size bitvector (bit i = slot i pending) — zero per-tensor
  // metadata in the warm regime.  `group` remembers the first announcer's
  // namespaced group tag so joined ranks batch synthesized entries exactly
  // like the peers' grouped entries; grouped-ness is part of the slot key,
  // so a rank flipping a tensor grouped<->ungrouped misses the cache, full-
  // announces, and trips the existing structure-divergence error.
  struct CacheRec {
    std::string name, digest, datadep, group;
    uint16_t required = 0;
    bool live = false;
    uint64_t last_used = 0;  // round counter, for LRU eviction
    // Speculation streak (protocol v7): consecutive rounds this slot was
    // ready-on-first-announce.  Prediction state hangs off the slot table
    // so every existing invalidation path (eviction, join-epoch flush,
    // relearn-after-digest-change) resets it for free: a reassigned or
    // relearned record starts from a zeroed streak.
    uint32_t streak = 0;
    // Per-slot instability backoff (ISSUE 12): mispredict count.  Each
    // mispredict doubles the streak this slot must rebuild before it is
    // predicted again (spec_ready_after << unstable, capped) — so a
    // chronically unstable slot (one rank's irregular announce pattern)
    // is WITHHELD from predictions instead of repeatedly entering them,
    // mispredicting, and zeroing every speculating client's engagement
    // streak for the stable slots too.  Stable slots keep speculating
    // (frame-guarded).  The penalty decays one step per kValidRunDecay
    // CONSECUTIVE validated predictions (valid_run) — deliberately much
    // slower than the escalation, so a slot that alternates short stable
    // stretches with mispredicts cannot oscillate back into predictions.
    uint32_t unstable = 0;
    uint32_t valid_run = 0;
  };
  static constexpr uint32_t kValidRunDecay = 16;
  // Bounded like the reference's capacity-limited cache; at capacity the
  // least-recently-used non-pending slot is evicted and the eviction is
  // broadcast, so client tables track the server's exactly.  An evicted
  // slot's RECORD stays intact and its id is only reusable from the NEXT
  // round: a client that bit-announced the slot in the same round the
  // eviction happened (it could not have known yet) must still resolve
  // against the old tuple — via the string verdict path — never against a
  // freshly reassigned one.
  size_t cache_capacity = 65536;
  size_t cache_live = 0;
  std::unordered_map<std::string, uint32_t> cache_keys;  // key -> slot
  std::vector<CacheRec> cache_recs;                      // slot -> record
  std::vector<uint32_t> cache_free;                      // reusable slots
  uint64_t round_no = 0;
  uint64_t announce_seq = 0;
  double stall_warn_s = 60.0;
  std::set<int> joined;
  int last_joined = -1;
  // Liveness (protocol v4): per-rank fault-tolerance capability (latched
  // from the request-side FLT1 ad) and the per-round deadline.  The
  // deadline is armed when a round's FIRST frame arrives — an idle fleet
  // (no rank negotiating) can never be declared dead, only a fleet where
  // some ranks reached the round and others failed to.  0 disables the
  // deadline; socket-death detection is always on.
  std::unique_ptr<std::atomic<char>[]> v4;
  int round_deadline_ms = 0;
  // Protocol v5: per-rank hierarchical capability (AGG5 ad / agent
  // handshake) and the accepted connections (loop-thread-only once the
  // world has assembled; server_stop severs through `fds`, which holds
  // every rank's serving fd — duplicated across an agent's ranks).
  // NB: nothing reads v5[] yet — the server sends no v5-only per-rank
  // sections (responses are rank-agnostic by design).  The latch exists
  // for protocol symmetry with v4[] so a future v5-gated section has its
  // capability record already on the wire; today it is diagnostic only.
  std::unique_ptr<std::atomic<char>[]> v5;
  // Protocol v6 (clean LEAVE): per-rank capability latch (round-1 LVE6
  // request ad; an agent's ranks latch from their forwarded round-1
  // subframes) and the set of ranks that departed cleanly.  eff_world()
  // is the readiness world every verdict materializes against.
  std::unique_ptr<std::atomic<char>[]> v6;
  std::set<int> left;
  // Protocol v7 (zero-RTT warm path): per-rank capability latch (round-1
  // ZRT7 request ad), the streak threshold (0 = speculation off), and the
  // slots predicted ready for the NEXT round (validated — and the
  // mispredicted slots' streaks reset — when that round's verdict lands).
  std::unique_ptr<std::atomic<char>[]> v7;
  int spec_ready_after = 0;
  // Elastic streak carryover (ISSUE 12): initial streak for NEWLY created
  // slots.  A survivor of a re-rendezvous passes the previous generation's
  // engagement hint through hvdtpu_server_start so the fresh slot table
  // re-predicts after ONE ready-on-first-announce round instead of
  // relearning spec_ready_after rounds from zero.  0 (default) = no seed.
  int spec_seed = 0;
  std::set<uint32_t> pred_slots;
  int pred_carry_rounds = 0;   // consecutive rounds a prediction carried
  // Diagnostic speculation accounting (not exported through the stats
  // ABI; the client-side counters are the observability surface).
  uint64_t spec_predictions = 0;
  uint64_t spec_confirms = 0;
  uint64_t spec_mispredicts = 0;
  int eff_world() const { return world - static_cast<int>(left.size()); }
  std::vector<Conn> conns;
  // Root-side service accounting (hvdtpu_server_stats): per-round time
  // from gather completion to the last response write — the serialized
  // root work the hierarchical control plane exists to shrink (parse +
  // verdict compute + one write per CONNECTION).  Client wall clocks
  // can't isolate this on a shared test box; the bench reads it directly.
  std::atomic<uint64_t> stat_rounds{0};
  std::atomic<uint64_t> stat_service_ns{0};

  void run();
  void run_inner();
  void broadcast_abort(const std::set<int>& dead, const std::string& why);
};

void Server::broadcast_abort(const std::set<int>& dead,
                             const std::string& why) {
  // Typed liveness verdict to surviving v4 clients; pre-v4 clients are
  // simply severed (run()'s epilogue shuts every socket down), which is
  // exactly the legacy rc=-1 failure they already understand.  One write
  // per CONNECTION: an agent gets the frame once and fans it to its
  // surviving local ranks itself.
  std::vector<uint8_t> resp;
  put_u32(&resp, kAbortEscape);
  put_u32(&resp, kAbortMagic);
  put_u32(&resp, static_cast<uint32_t>(dead.size()));
  for (int r : dead) put_u32(&resp, static_cast<uint32_t>(r));
  put_str(&resp, why);
  for (Conn& c : conns) {
    if (c.sock_dead || c.left || c.fd < 0) continue;
    bool any_live_v4 = false;
    for (int r : c.ranks)
      if (!dead.count(r) && v4[r].load()) any_live_v4 = true;
    if (any_live_v4) write_frame(c.fd, resp);
  }
}

void Server::run() {
  run_inner();
  // Whatever ended the loop (peer death, accept failure, stop), surviving
  // clients must see EOF rather than hang in read_frame.  shutdown only —
  // close stays with server_stop after the join (fd-recycling discipline).
  for (int r = 0; r < world; ++r) {
    int fd = fds[r].load();
    if (fd >= 0) ::shutdown(fd, SHUT_RDWR);
  }
}

void Server::run_inner() {
  // Accept until every rank is claimed: one connection per rank (flat
  // mode), or one per-host agent connection claiming several ranks
  // (protocol v5 — hello word kAgentHello outside the rank space, then a
  // rank-list frame).  All accepted fds land in `fds` (one slot per
  // claimed rank; an agent's fd is duplicated across its ranks) so
  // server_stop's cleanup owns closing them — run() never closes a
  // registered fd, which avoids shutdown() on a recycled fd number.
  int claimed = 0;
  while (claimed < world && !stop.load()) {
    int fd = ::accept(listen_fd, nullptr, nullptr);
    if (fd < 0) return;
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    handshake_fd.store(fd);
    if (stop.load()) {  // stop raced the accept; don't block in the read
      if (handshake_fd.exchange(-1) != -2) ::close(fd);
      return;
    }
    uint32_t hello = 0;
    bool ok = read_exact(fd, &hello, 4);
    bool is_agent = ok && hello == kAgentHello;
    std::vector<uint8_t> rank_list;
    if (is_agent) ok = read_frame(fd, &rank_list);
    // Ownership handoff: if server_stop already exchanged the slot to -2 it
    // owns shutdown() on this fd, so we must not close it (the number could
    // be recycled under its feet); we're stopping anyway.
    if (handshake_fd.exchange(-1) == -2) return;
    Conn conn;
    conn.fd = fd;
    conn.is_agent = is_agent;
    if (ok && is_agent) {
      Reader rd{rank_list.data(), rank_list.data() + rank_list.size()};
      rd.u32();  // host index: diagnostic only
      uint32_t n = rd.u32();
      std::set<int> uniq;
      for (uint32_t i = 0; i < n && rd.ok; ++i) {
        uint32_t r = rd.u32();
        if (!rd.ok || r >= static_cast<uint32_t>(world)
            || fds[r].load() >= 0 || !uniq.insert(int(r)).second) {
          rd.ok = false;
          break;
        }
        conn.ranks.push_back(static_cast<int>(r));
      }
      ok = rd.ok && !conn.ranks.empty();
    } else if (ok) {
      if (hello >= static_cast<uint32_t>(world) || fds[hello].load() >= 0)
        ok = false;
      else
        conn.ranks.push_back(static_cast<int>(hello));
    }
    if (!ok) {
      ::close(fd);
      continue;
    }
    for (int r : conn.ranks) {
      fds[r].store(fd);
      if (is_agent) {
        // The agent handshake IS the v4+v5 capability proof: agents only
        // exist in v5 builds, and they fan typed aborts down to their
        // local ranks themselves.
        v4[r].store(1);
        v5[r].store(1);
      }
    }
    claimed += static_cast<int>(conn.ranks.size());
    conns.push_back(std::move(conn));
  }
  for (int r = 0; r < world; ++r)
    if (fds[r].load() < 0) return;  // stopped before the world assembled
  // Deterministic processing order: connections sorted by first rank, so
  // announce_seq ordering matches the flat per-rank gather's rank order.
  std::sort(conns.begin(), conns.end(), [](const Conn& a, const Conn& b) {
    return a.ranks.front() < b.ranks.front();
  });
  // Readiness multiplexer, registered ONCE: the old gather rebuilt a
  // pollfd set and issued a bounded blocking read per readable fd every
  // round — O(ranks) setup + the risk of blocking inside one peer's
  // half-written frame.  Frames now reassemble per connection off
  // non-blocking reads, and root-side gather work is one event + one
  // frame + one response write per CONNECTION (= per host under the
  // hierarchical control plane).
  Poller poller;
  for (size_t i = 0; i < conns.size(); ++i)
    poller.add(conns[i].fd, static_cast<int>(i));

  // Gather-phase containers, hoisted out of the round loop and cleared
  // per round so each connection's frame buffer keeps its capacity across
  // rounds — the steady-state warm path (13-byte frames) allocates
  // nothing here, matching the pre-v4 reusable frame buffer.
  std::vector<std::vector<uint8_t>> round_frames(conns.size());
  std::vector<char> have_frame(conns.size(), 0);
  std::set<int> dead_conn, dead_late;
  std::vector<int> ready_idx;

  while (!stop.load()) {
    ++round_no;
    // One lock-step round: a frame from every rank, then a reply to all.
    // Cache assignments created/confirmed this round, broadcast to all
    // ranks in the response (deduped; a client only adopts assignments
    // for names it announced itself).
    // value = the FULL cache key (name, digest, datadep, required) so a
    // client adopting the id can match it against exactly the tuple it
    // announced — two announces sharing (name, digest) but differing in
    // datadep/required (same tensor name under different process sets)
    // must not cross-adopt each other's ids.
    struct AssignRec {
      std::string name, digest, datadep;
      uint16_t required;
      uint16_t grouped;  // part of the slot key; echoed so clients adopt
                         // against exactly the tuple they announced
    };
    std::map<uint32_t, AssignRec> assigns;
    std::vector<uint32_t> evictions;   // ids freed this round: broadcast,
                                       // reusable only from the next round
    // Monitor blobs received this round (rank, opaque payload) — pure
    // store-and-forward: re-broadcast in this round's response so every
    // client's aggregation table tracks the fleet.  The server never
    // parses the payload.
    std::vector<std::pair<int, std::string>> mon_blobs;
    // Ranks whose clean LEAVE (protocol v6) was processed this round —
    // broadcast to survivors in the trailing LVE6 response section.
    std::vector<int> left_this_round;
    bool join_started = false;
    // slot: >= 0 answers may ride the ready bitvector; -1 forces strings.
    auto handle_announce = [&](int r, uint16_t required,
                               const std::string& name,
                               const std::string& digest,
                               const std::string& group,
                               const std::string& datadep, int64_t slot) {
      auto it = pending.find(name);
      if (it == pending.end()) {
        PendingInfo info;
        info.order = announce_seq++;
        info.required = required;   // raw: 0 = full (effective) world
        info.first_seen = Clock::now();
        info.round_created = round_no;
        info.digest = digest;
        info.group = group == "-1" ? group : std::to_string(r) + ":" + group;
        info.data_dep = datadep.empty() ? -1 : std::atoi(datadep.c_str());
        it = pending.emplace(name, std::move(info)).first;
      }
      it->second.ready_ranks.insert(r);
      it->second.by_digest[digest].insert(r);
      (group == "-1" ? it->second.ungrouped_ranks
                     : it->second.grouped_ranks)
          .insert(r);
      // Slot eligibility is sticky-downward: every announcing rank must be
      // able to resolve a slot-bit verdict (slot known or assigned this
      // same round), else the verdict stays on the string path.
      if (slot < 0 || (it->second.slot != INT64_MIN && it->second.slot < 0))
        it->second.slot = -1;
      else
        it->second.slot = slot;
      if (digest != it->second.digest) {
        // Divergent submission (reference controller's consistency
        // check).  The message is rebuilt at response time so late
        // announcers still appear in the rank attribution.
        it->second.errored = true;
      }
    };
    // Evictions reclaim least-recently-used live slots not referenced by
    // a pending negotiation; broadcast so clients drop them in lock-step.
    // ONE candidate scan + sort per round (built lazily, only under
    // capacity pressure), validated per pop — so a digest-churning
    // workload (new key every announce, table pinned at capacity) costs
    // one O(capacity log capacity) pass per round, and the per-round
    // budget degrades the overflow to string-path negotiation (correct
    // either way) instead of burning the rank-0 hot path.
    int evict_budget = 256;
    std::vector<uint32_t> evict_queue;   // LRU-ascending candidates
    size_t evict_pos = 0;
    bool evict_queue_built = false;
    auto evict_lru = [&]() -> bool {
      if (evict_budget <= 0) return false;
      if (!evict_queue_built) {
        evict_queue_built = true;
        std::vector<std::pair<uint64_t, uint32_t>> cands;
        cands.reserve(cache_live);
        for (size_t i = 0; i < cache_recs.size(); ++i)
          if (cache_recs[i].live)
            cands.emplace_back(cache_recs[i].last_used,
                               static_cast<uint32_t>(i));
        std::sort(cands.begin(), cands.end());
        evict_queue.reserve(cands.size());
        for (auto& c : cands) evict_queue.push_back(c.second);
      }
      auto evict_one = [&](uint32_t victim) {
        CacheRec& rec = cache_recs[victim];
        --evict_budget;
        std::string key = rec.name;
        key += '\x1f';
        key += rec.digest;
        key += '\x1f';
        key += rec.datadep;
        key += '\x1f';
        key += std::to_string(rec.required);
        key += '\x1f';
        key += rec.group == "-1" ? '0' : '1';
        cache_keys.erase(key);
        rec.live = false;  // record kept intact for same-round bit
        --cache_live;      // resolves; id reusable only after the round
        evictions.push_back(victim);
      };
      while (evict_pos < evict_queue.size()) {
        uint32_t victim = evict_queue[evict_pos++];
        CacheRec& rec = cache_recs[victim];
        // Revalidate at pop time: the slot may have been used (bit
        // announce / confirm) or referenced by a fresh pending entry
        // since the queue was built.
        if (!rec.live || rec.last_used == round_no) continue;
        // GROUP-ATOMIC eviction: every live record sharing the victim's
        // group tag goes with it.  A group announces atomically, so all
        // its records were learned in the same round and their frozen
        // tags agree ("same tag ⇒ same version"); a PARTIAL eviction
        // breaks that — the relearned member freezes a fresh per-step
        // tag while survivors keep the old one, and in the one boundary
        // round where a join announce lands beside peers' bit announces
        // the joined rank's synthesizer would see one logical group
        // under two tags (split clusters, divergent batching at the
        // fusion threshold).  Evicting the whole group keeps the
        // invariant: live same-group records always carry one tag.
        std::vector<uint32_t> victims;
        victims.push_back(victim);
        if (rec.group != "-1") {
          victims.clear();
          for (size_t i = 0; i < cache_recs.size(); ++i)
            if (cache_recs[i].live && cache_recs[i].group == rec.group)
              victims.push_back(static_cast<uint32_t>(i));
        }
        bool blocked = false;
        for (uint32_t v : victims) {
          if (cache_recs[v].last_used == round_no) {
            blocked = true;  // a sibling is hot this round: skip the group
            break;
          }
          for (auto& [n, info] : pending)
            if (info.slot == static_cast<int64_t>(v)) {
              blocked = true;
              break;
            }
          if (blocked) break;
        }
        if (blocked) continue;
        // The whole group is evicted even when it overruns the per-round
        // budget — a partial group eviction is exactly the hazard.
        for (uint32_t v : victims) evict_one(v);
        return true;
      }
      evict_budget = 0;    // candidates exhausted: stop for this round
      return false;
    };
    // ---- gather phase (protocol v4 liveness): ONE frame per connection,
    // collected through the readiness multiplexer with per-connection
    // reassembly, so a dead socket (recv 0 / ECONNRESET), an agent's
    // dead-local-rank report, or a missed round deadline turns into a
    // typed ABORT to the survivors — and a peer wedged mid-frame-write
    // can never block the gather (its bytes just sit in the reassembly
    // buffer until the deadline names it).  Frames are still PROCESSED in
    // rank order below, so announce_seq ordering (and with it the
    // deterministic ready order) is unchanged from the serial protocol.
    for (size_t i = 0; i < conns.size(); ++i) {
      round_frames[i].clear();
      have_frame[i] = 0;
    }
    dead_conn.clear();
    dead_late.clear();
    bool deadline_armed = false;
    Clock::time_point deadline_tp{};
    // Take this round's frame for connection i (from the reassembly
    // queue), arm the deadline at the round's FIRST complete frame (an
    // idle fleet can never be declared dead — only ranks that failed to
    // reach a round their peers already reached), and peek an agent
    // uplink's dead-rank section: a local rank death the agent observed
    // is a root-level liveness verdict with exact rank attribution.
    auto take_frame = [&](size_t i) {
      round_frames[i] = std::move(conns[i].frames.front());
      conns[i].frames.erase(conns[i].frames.begin());
      have_frame[i] = 1;
      if (!deadline_armed && round_deadline_ms > 0) {
        deadline_armed = true;
        deadline_tp = Clock::now() +
                      std::chrono::milliseconds(round_deadline_ms);
      }
      if (conns[i].is_agent) {
        const std::vector<uint8_t>& f = round_frames[i];
        const std::vector<int>& claimed = conns[i].ranks;
        Reader rd{f.data(), f.data() + f.size()};
        if (rd.u32() == kHupMagic && rd.ok) {
          uint32_t nd = rd.u32();
          for (uint32_t k = 0; k < nd && rd.ok; ++k) {
            uint32_t r = rd.u32();
            // Membership check: an agent may only declare ITS OWN ranks
            // dead — a corrupted uplink must not abort a healthy rank on
            // another host.
            if (rd.ok && std::find(claimed.begin(), claimed.end(),
                                   static_cast<int>(r)) != claimed.end())
              dead_conn.insert(static_cast<int>(r));
          }
        }
      }
    };
    // Leftover frames (they reassembled while the previous round was
    // still writing responses) satisfy this round immediately; a
    // connection that died after delivering its last frame is found dead
    // here, not silently skipped.
    int pending_frames = 0;
    for (size_t i = 0; i < conns.size(); ++i) {
      if (conns[i].left) continue;   // departed cleanly: not in this round
      if (!conns[i].frames.empty()) {
        take_frame(i);
      } else if (conns[i].sock_dead) {
        for (int r : conns[i].ranks) dead_conn.insert(r);
      } else {
        ++pending_frames;
      }
    }
    // Grace drain for the failure-at-startup class: when a rank dies in
    // round 1, survivors that have not yet SENT their round-1 frame have
    // not advertised FLT1 either — aborting immediately would sever them
    // with the untyped legacy rc=-1.  So after a death the gather keeps
    // collecting frames from live ranks whose capability is still
    // unknown, for a bounded window; once every live rank is either
    // latched v4 or has its frame in hand (the common case within
    // milliseconds — peers are in lock-step and about to send anyway),
    // the abort goes out.  Rounds where every survivor is already
    // latched (any round past the first) break immediately as before.
    constexpr int kAbortGraceMs = 2000;
    bool grace_armed = false;
    Clock::time_point grace_tp{};
    while (pending_frames > 0 && !stop.load() && dead_late.empty()) {
      // Short wait quantum keeps the loop responsive to server_stop (the
      // pre-v4 design relied on stop shutting the socket under a blocked
      // recv; poller wakeups serve the same purpose with a bound).
      int timeout = 100;
      if (deadline_armed) {
        auto rem = std::chrono::duration_cast<std::chrono::milliseconds>(
                       deadline_tp - Clock::now())
                       .count();
        if (rem <= 0) {
          // Final non-blocking drain before the verdict: a frame already
          // buffered in the kernel at expiry proves its sender reached
          // the round — declaring it dead would abort the fleet with a
          // verdict naming a healthy rank.
          for (size_t i = 0; i < conns.size(); ++i) {
            if (have_frame[i] || conns[i].sock_dead || conns[i].left)
              continue;
            conns[i].drain();
            if (!conns[i].frames.empty()) {
              take_frame(i);
              --pending_frames;
            }
          }
          for (size_t i = 0; i < conns.size(); ++i) {
            if (have_frame[i] || conns[i].left) continue;
            if (conns[i].sock_dead) {
              poller.remove(conns[i].fd);
              for (int r : conns[i].ranks) dead_conn.insert(r);
            } else {
              // Mid-frame wedge or silence: the connection reached (or
              // never reached) the round but missed its deadline.
              for (int r : conns[i].ranks) dead_late.insert(r);
            }
          }
          break;
        }
        timeout = static_cast<int>(std::min<int64_t>(timeout, rem));
      }
      int n = poller.wait(timeout, &ready_idx);
      if (n < 0) {
        if (errno == EINTR) continue;
        stop.store(true);
        break;
      }
      for (int idx : ready_idx) {
        Conn& c = conns[static_cast<size_t>(idx)];
        if (c.sock_dead) continue;
        c.drain();
        if (!have_frame[idx] && !c.frames.empty()) {
          take_frame(static_cast<size_t>(idx));
          --pending_frames;
        }
        if (c.sock_dead) {
          // Removed from the poller either way (a dead level-triggered fd
          // would spin the loop); if the round's frame never arrived,
          // these ranks are this round's verdict.
          poller.remove(c.fd);
          if (!have_frame[idx])
            for (int r : c.ranks) dead_conn.insert(r);
        }
      }
      if (!dead_late.empty()) break;  // deadline verdict: abort the round
      if (!dead_conn.empty()) {
        bool awaiting_ad = false;
        for (size_t i = 0; i < conns.size(); ++i) {
          if (have_frame[i] || conns[i].sock_dead || conns[i].left)
            continue;
          for (int r : conns[i].ranks)
            if (!dead_conn.count(r) && !v4[r].load()) {
              awaiting_ad = true;
              break;
            }
          if (awaiting_ad) break;
        }
        if (!awaiting_ad) break;
        auto now = Clock::now();
        if (!grace_armed) {
          grace_armed = true;
          grace_tp = now + std::chrono::milliseconds(kAbortGraceMs);
        } else if (now >= grace_tp) {
          break;
        }
      }
    }
    if (!stop.load() && (!dead_conn.empty() || !dead_late.empty())) {
      // Salvage still-buffered frames from live connections before the
      // verdict: frames may have landed since the last poller wakeup.
      // Most importantly this recovers round 1's trailing FLT1 capability
      // ads — without the frame, v4[] never latches and the survivor gets
      // the untyped legacy sever (unattributed rc=-1) instead of the
      // typed ABORT.
      for (size_t i = 0; i < conns.size(); ++i) {
        if (have_frame[i] || conns[i].sock_dead || conns[i].left) continue;
        bool all_dead = true;
        for (int r : conns[i].ranks)
          if (!dead_conn.count(r) && !dead_late.count(r)) all_dead = false;
        if (all_dead) continue;
        conns[i].drain();
        if (!conns[i].frames.empty()) take_frame(i);
      }
      auto list = [](const std::set<int>& s) {
        std::string out;
        for (int r : s) {
          if (!out.empty()) out += ",";
          out += std::to_string(r);
        }
        return out;
      };
      if (std::getenv("HVD_TPU_COORD_DEBUG") != nullptr) {
        for (size_t i = 0; i < conns.size(); ++i)
          fprintf(stderr,
                  "[coord] round=%llu conn=%zu ranks0=%d agent=%d left=%d "
                  "have=%d dead=%d errno=%d inbuf=%zu frames=%zu\n",
                  (unsigned long long)round_no, i,
                  conns[i].ranks.empty() ? -1 : conns[i].ranks.front(),
                  (int)conns[i].is_agent, (int)conns[i].left,
                  (int)have_frame[i],
                  (int)conns[i].sock_dead, conns[i].dead_errno,
                  conns[i].inbuf.size(), conns[i].frames.size());
      }
      std::string why;
      if (!dead_conn.empty())
        why += "rank(s) [" + list(dead_conn) +
               "] lost connection mid-negotiation (process crash, "
               "ECONNRESET, or network failure)";
      if (!dead_late.empty()) {
        if (!why.empty()) why += "; ";
        why += "rank(s) [" + list(dead_late) + "] missed the " +
               std::to_string(round_deadline_ms) +
               "ms round deadline (hung or wedged)";
      }
      why += " in negotiation round " + std::to_string(round_no);
      std::set<int> all_dead = dead_conn;
      all_dead.insert(dead_late.begin(), dead_late.end());
      // A death in round 1 finds the FLT1 capability ads still sitting in
      // the gathered-but-unPROCESSED frames (processing only starts once
      // every rank's frame is in), so v4[] would gate the abort away from
      // every survivor and the fleet would fail with the untyped legacy
      // rc=-1 — losing dead-rank attribution exactly for the failure-at-
      // startup class.  Latch the ads now: the client contract
      // (controller.py) appends FLT1 as the FINAL trailing section of the
      // round-1 request (AGG5 rides before it), so the ad is exactly the
      // frame's last 8 bytes.  Agent connections were latched at
      // handshake and need no salvage.
      for (size_t i = 0; i < conns.size(); ++i) {
        if (!have_frame[i] || conns[i].is_agent) continue;
        int r = conns[i].ranks.front();
        if (v4[r].load()) continue;
        const std::vector<uint8_t>& f = round_frames[i];
        if (f.size() < 8) continue;
        uint32_t magic = 0, blen = 0;
        std::memcpy(&magic, f.data() + f.size() - 8, 4);
        std::memcpy(&blen, f.data() + f.size() - 4, 4);
        if (magic == kFltMagic && blen == 0) v4[r].store(1);
      }
      broadcast_abort(all_dead, why);
      stop.store(true);
      break;
    }
    if (stop.load()) break;
    auto svc_t0 = Clock::now();   // gather complete: root service begins
    // One rank's frame (a flat connection's round frame, or one agent
    // subframe — byte-identical to what the rank itself sent).
    auto process_rank_frame = [&](int r, const uint8_t* fdata, size_t flen) {
      Reader rd{fdata, fdata + flen};
      // Sanitizer tag side-channel for this rank's bitvector announces
      // (slot -> tag); parsed after the bitvector but needed while
      // resolving it, so the sections are walked full -> bits -> tags and
      // bit announces are resolved afterwards.
      std::vector<uint32_t> bit_slots;
      uint32_t n = rd.u32();
      for (uint32_t i = 0; i < n && rd.ok; ++i) {
        uint16_t required = rd.u16();
        std::string name = rd.str();
        std::string digest = rd.str();
        std::string group = rd.str();
        std::string datadep = rd.str();
        std::string tag = rd.str();
        if (name == "\x1f__join__") {
          joined.insert(r);
          last_joined = r;
          join_started = true;
          continue;
        }
        // Assign (or confirm) the tuple's cache slot so every announcer
        // eventually learns it and drops to the bitvector form.  The key
        // excludes the sanitizer tag (per-submission, never repeats) but
        // includes grouped-ness (see CacheRec comment).  No assignments
        // while any rank is joined: the epoch started with a table flush,
        // and relearning mid-epoch would freeze per-step group tags into
        // slot records while the joined rank's synthesizer still consumes
        // them — full announces (with CURRENT tags) for the whole epoch
        // keep grouped batching exact; slots relearn once the world
        // resumes.
        if (!joined.empty()) {
          std::string eff0 = tag.empty() ? digest : digest + "|" + tag;
          handle_announce(r, required, name, eff0, group, datadep, -1);
          continue;
        }
        std::string key = name;
        key += '\x1f';
        key += digest;
        key += '\x1f';
        key += datadep;
        key += '\x1f';
        key += std::to_string(required);
        key += '\x1f';
        key += group == "-1" ? '0' : '1';
        auto ck = cache_keys.find(key);
        if (ck == cache_keys.end()) {
          if (cache_live >= cache_capacity && cache_capacity > 0)
            evict_lru();
          if (cache_live < cache_capacity) {
            uint32_t id;
            if (!cache_free.empty()) {
              id = cache_free.back();
              cache_free.pop_back();
            } else {
              id = static_cast<uint32_t>(cache_recs.size());
              cache_recs.push_back(CacheRec{});
            }
            std::string g = group == "-1"
                ? group : std::to_string(r) + ":" + group;
            cache_recs[id] = CacheRec{name, digest, datadep, g, required,
                                      true, round_no};
            // Streak carryover: a seeded fresh slot matures on its FIRST
            // ready-on-first-announce round (seed + 1 >= spec_ready_after),
            // re-engaging warm speculation in O(1) rounds after an elastic
            // re-rendezvous instead of relearning from zero.
            if (spec_seed > 0)
              cache_recs[id].streak = static_cast<uint32_t>(spec_seed);
            cache_keys.emplace(key, id);
            ++cache_live;
            ck = cache_keys.find(key);
          }
        }
        int64_t slot = -1;
        if (ck != cache_keys.end()) {
          slot = ck->second;
          cache_recs[ck->second].last_used = round_no;
          assigns[ck->second] = AssignRec{
              name, digest, datadep, required,
              static_cast<uint16_t>(group == "-1" ? 0 : 1)};
        }
        std::string eff = tag.empty() ? digest : digest + "|" + tag;
        handle_announce(r, required, name, eff, group, datadep, slot);
      }
      // Bitvector section: slot i pending on this rank.
      if (rd.ok && rd.p < rd.end) {
        uint32_t nbytes = rd.u32();
        for (uint32_t b = 0; b < nbytes && rd.ok; ++b) {
          if (rd.p >= rd.end) { rd.ok = false; break; }
          uint8_t byte = *rd.p++;
          for (int bit = 0; bit < 8; ++bit)
            if (byte & (1u << bit)) bit_slots.push_back(b * 8 + bit);
        }
      }
      // Sanitizer tag side-channel (sparse; empty outside sanitizer mode).
      std::map<uint32_t, std::string> bit_tags;
      if (rd.ok && rd.p < rd.end) {
        uint32_t nt = rd.u32();
        for (uint32_t i = 0; i < nt && rd.ok; ++i) {
          uint32_t slot = rd.u32();
          bit_tags[slot] = rd.str();
        }
      }
      // Optional trailing sections, walked generically as (magic, len,
      // payload) tuples so protocol extensions compose in any order and
      // unknown magics are skipped.  MON1 (protocol v3): an opaque
      // telemetry blob for store-and-forward — a malformed/truncated
      // section is dropped without failing the round (telemetry must
      // never cost negotiation), and oversized blobs (> kMonBlobCap) are
      // dropped so the re-broadcast never pushes a response past the
      // client's fixed receive buffer.  FLT1 (protocol v4): the client's
      // fault-tolerance capability ad, sent on its first round only —
      // latches the rank as eligible for the typed ABORT frame.
      while (rd.ok && rd.p + 8 <= rd.end) {
        uint32_t magic = rd.u32();
        uint32_t blen = rd.u32();
        if (!rd.ok || rd.p + blen > rd.end) break;
        if (magic == kMonMagic) {
          if (blen <= kMonBlobCap)
            mon_blobs.emplace_back(
                r, std::string(reinterpret_cast<const char*>(rd.p), blen));
        } else if (magic == kFltMagic) {
          v4[r].store(1);
        } else if (magic == kAggMagic) {
          v5[r].store(1);
        } else if (magic == kLeaveMagic) {
          v6[r].store(1);
        } else if (magic == kZrtMagic) {
          // Empty payload: the round-1 capability ad.  One byte 0x01: the
          // rank consumed last round's prediction and dispatched its
          // verdict speculatively (accounting only — the announce itself
          // already rides the ordinary bitvector section).
          v7[r].store(1);
          if (blen >= 1 && *rd.p == 1) ++spec_confirms;
        }
        rd.p += blen;
      }
      for (uint32_t id : bit_slots) {
        // A non-live slot with an intact record was evicted THIS round
        // (ids are only reused from the next round, and the announcing
        // client sees the eviction broadcast before its next request):
        // the announce must still count — resolved via the old tuple,
        // answered on the string path (slot hint -1) — or the tensor
        // would wedge with the client believing it announced.
        if (id >= cache_recs.size() || cache_recs[id].name.empty())
          continue;
        CacheRec& rec = cache_recs[id];
        int64_t hint = rec.live ? static_cast<int64_t>(id) : -1;
        if (rec.live) rec.last_used = round_no;
        auto tg = bit_tags.find(id);
        std::string eff = tg == bit_tags.end()
            ? rec.digest : rec.digest + "|" + tg->second;
        // rec.group is already namespaced by its first announcer; pass
        // "-1" vs non-"-1" through (handle_announce re-namespaces only
        // raw tags, so hand it the raw suffix when grouped).
        auto it = pending.find(rec.name);
        bool fresh = it == pending.end();
        if (fresh) {
          PendingInfo info;
          info.order = announce_seq++;
          info.required = rec.required;   // raw: 0 = full world
          info.first_seen = Clock::now();
          info.round_created = round_no;
          info.digest = eff;
          info.group = rec.group;
          info.data_dep =
              rec.datadep.empty() ? -1 : std::atoi(rec.datadep.c_str());
          info.slot = hint;
          it = pending.emplace(rec.name, std::move(info)).first;
        }
        it->second.ready_ranks.insert(r);
        it->second.by_digest[eff].insert(r);
        (rec.group == "-1" ? it->second.ungrouped_ranks
                           : it->second.grouped_ranks)
            .insert(r);
        if (!fresh) {
          if (hint < 0)
            it->second.slot = -1;
          else if (it->second.slot == INT64_MIN)
            it->second.slot = hint;
          if (eff != it->second.digest) it->second.errored = true;
        }
      }
    };
    // Aggregate warm-path announce (protocol v5): one fixed-size bitvector
    // that counts for EVERY rank its agent speaks for.  The agent only
    // emits it when all its local ranks sent identical pure-warm frames,
    // so per-rank semantics (readiness counting, stall attribution, digest
    // consistency) reduce to inserting each covered rank; sanitizer-tagged
    // frames are forwarded per-rank by construction, so the aggregate
    // digest is always the slot record's untagged one.
    auto process_agg_bits = [&](const std::vector<int>& ranks,
                                const uint8_t* bv, uint32_t nbytes) {
      for (uint32_t b = 0; b < nbytes; ++b) {
        uint8_t byte = bv[b];
        if (!byte) continue;
        for (int bit = 0; bit < 8; ++bit) {
          if (!(byte & (1u << bit))) continue;
          uint32_t id = b * 8 + bit;
          // Same evicted-this-round contract as the per-rank bit path: a
          // non-live slot with an intact record still resolves, on the
          // string path.
          if (id >= cache_recs.size() || cache_recs[id].name.empty())
            continue;
          CacheRec& rec = cache_recs[id];
          int64_t hint = rec.live ? static_cast<int64_t>(id) : -1;
          if (rec.live) rec.last_used = round_no;
          const std::string& eff = rec.digest;
          auto it = pending.find(rec.name);
          bool fresh = it == pending.end();
          if (fresh) {
            PendingInfo info;
            info.order = announce_seq++;
            info.required = rec.required;   // raw: 0 = full world
            info.first_seen = Clock::now();
            info.round_created = round_no;
            info.digest = eff;
            info.group = rec.group;
            info.data_dep =
                rec.datadep.empty() ? -1 : std::atoi(rec.datadep.c_str());
            info.slot = hint;
            it = pending.emplace(rec.name, std::move(info)).first;
          }
          for (int r : ranks) {
            it->second.ready_ranks.insert(r);
            it->second.by_digest[eff].insert(r);
            (rec.group == "-1" ? it->second.ungrouped_ranks
                               : it->second.grouped_ranks)
                .insert(r);
          }
          if (!fresh) {
            if (hint < 0)
              it->second.slot = -1;
            else if (it->second.slot == INT64_MIN)
              it->second.slot = hint;
            if (eff != it->second.digest) it->second.errored = true;
          }
        }
      }
    };
    // Clean LEAVE (protocol v6): drop the rank from the gather with no
    // dead-peer verdict.  Honored only when every survivor latched v6 —
    // a pre-v6 survivor cannot parse the leave notice and would execute
    // shrunk-world verdicts its fixed-size data plane cannot resolve —
    // otherwise the LEAVE is ignored and the leaver's subsequent socket
    // sever produces the legacy v4 verdict.  The ONE abort case: the
    // leaver still has outstanding negotiated work (a pending tensor it
    // announced, or an implicit world-level credit while joined) whose
    // readiness would include a rank that will never execute it.
    auto handle_leave = [&](int r, Conn& c) {
      if (left.count(r)) return;
      for (int rr = 0; rr < world; ++rr) {
        if (rr == r || left.count(rr) || v6[rr].load()) continue;
        return;   // pre-v6 survivor: degrade to the legacy sever path
      }
      std::string stuck;
      for (auto& [n, info] : pending) {
        bool involved = info.ready_ranks.count(r) > 0;
        if (!involved && joined.count(r) && info.required == 0 &&
            n.find('\x1f') == std::string::npos)
          involved = true;   // joined rank: implicit world-level credit
        if (involved) {
          stuck = n;
          break;
        }
      }
      if (!stuck.empty()) {
        broadcast_abort(std::set<int>{r},
                        "rank " + std::to_string(r) +
                            " sent a clean LEAVE with outstanding "
                            "negotiated work (tensor '" + stuck +
                            "') in round " + std::to_string(round_no));
        stop.store(true);
        return;
      }
      left.insert(r);
      left_this_round.push_back(r);
      joined.erase(r);
      if (c.is_agent) {
        // The host's uplink SHRINKS instead of dying: the agent keeps
        // speaking for its remaining ranks (its own uplink already
        // dropped the leaver); only the last local rank's departure
        // retires the whole connection.
        c.ranks.erase(std::remove(c.ranks.begin(), c.ranks.end(), r),
                      c.ranks.end());
        if (c.ranks.empty()) {
          c.left = true;
          poller.remove(c.fd);
        }
      } else {
        c.left = true;
        poller.remove(c.fd);
      }
    };
    // Dispatch this round's frames in connection (= ascending first-rank)
    // order: flat frames parse exactly as before; an agent uplink unpacks
    // into its aggregate section, verbatim per-rank subframes, and
    // deduplicated MON1 blobs.
    for (size_t ci = 0; ci < conns.size(); ++ci) {
      Conn& c = conns[ci];
      if (c.left || stop.load()) continue;
      const std::vector<uint8_t>& f = round_frames[ci];
      if (!c.is_agent) {
        if (is_leave_frame(f.data(), f.size())) {
          handle_leave(c.ranks.front(), c);
          continue;
        }
        process_rank_frame(c.ranks.front(), f.data(), f.size());
        continue;
      }
      Reader rd{f.data(), f.data() + f.size()};
      if (rd.u32() != kHupMagic || !rd.ok) continue;  // malformed: dropped
      uint32_t nd = rd.u32();
      for (uint32_t k = 0; k < nd && rd.ok; ++k) rd.u32();  // peeked in gather
      uint32_t agg_n = rd.u32();
      if (rd.ok && agg_n > 0) {
        uint32_t nbytes = rd.u32();
        if (rd.ok && rd.p + nbytes <= rd.end) {
          process_agg_bits(c.ranks, rd.p, nbytes);
          rd.p += nbytes;
        } else {
          rd.ok = false;
        }
      }
      // Membership check on every per-rank section: an agent speaks ONLY
      // for its claimed ranks — a corrupted uplink must not announce (or
      // attribute telemetry) on behalf of another host's ranks.
      auto owns = [&c](uint32_t r) {
        return std::find(c.ranks.begin(), c.ranks.end(),
                         static_cast<int>(r)) != c.ranks.end();
      };
      uint32_t n_sub = rd.ok ? rd.u32() : 0;
      for (uint32_t k = 0; k < n_sub && rd.ok; ++k) {
        uint32_t r = rd.u32();
        uint32_t flen = rd.u32();
        if (!rd.ok || rd.p + flen > rd.end) break;
        if (owns(r)) {
          // A local rank's clean LEAVE travels as a verbatim subframe
          // (the agent cannot aggregate it): same semantics as flat mode,
          // but the HOST connection persists for the remaining ranks.
          if (is_leave_frame(rd.p, flen))
            handle_leave(static_cast<int>(r), c);
          else
            process_rank_frame(static_cast<int>(r), rd.p, flen);
        }
        rd.p += flen;
        if (stop.load()) break;
      }
      uint32_t n_mon = rd.ok ? rd.u32() : 0;
      for (uint32_t k = 0; k < n_mon && rd.ok; ++k) {
        uint32_t r = rd.u32();
        uint32_t blen = rd.u32();
        if (!rd.ok || rd.p + blen > rd.end) break;
        if (blen <= kMonBlobCap && owns(r))
          mon_blobs.emplace_back(
              static_cast<int>(r),
              std::string(reinterpret_cast<const char*>(rd.p), blen));
        rd.p += blen;
      }
    }
    if (stop.load()) break;
    if (eff_world() <= 0) break;   // every rank departed cleanly: done
    if (join_started) {
      // A join epoch begins: flush every slot (broadcast as evictions) so
      // the whole epoch renegotiates in full — joined ranks need digest
      // strings to synthesize, and stale per-step group structure must not
      // outlive the epoch.  Clients relearn slots once the world resumes.
      for (size_t i = 0; i < cache_recs.size(); ++i) {
        if (!cache_recs[i].live) continue;
        cache_recs[i].live = false;
        evictions.push_back(static_cast<uint32_t>(i));
      }
      cache_keys.clear();
      cache_live = 0;
      assigns.clear();
      for (auto& [n, info] : pending) info.slot = -1;
    }
    // Compute+write under phase_mu: see the field's comment.  Reads stay
    // outside the lock (they block on peers, and server_stop must be able
    // to sever a blocked read).
    std::lock_guard<std::timed_mutex> phase_lock(phase_mu);

    // Ready = reported by every rank (joined ranks count as implicitly
    // ready for world-level tensors); deterministic order by announce seq.
    // Errored tensors are never ready: their error is broadcast every round
    // until all required ranks have announced (so each has a local entry to
    // fail), then dropped.
    std::vector<std::tuple<uint64_t, std::string, std::string, std::string>>
        ready;
    std::vector<uint32_t> ready_slots;
    // Parallel to ready_slots: announce and ready landed in the SAME
    // round — the speculation streak's increment condition (v7).
    std::vector<char> ready_slot_first;
    std::vector<std::string> warns;
    std::vector<std::pair<std::string, std::string>> errs;
    auto now = Clock::now();
    for (auto it = pending.begin(); it != pending.end();) {
      auto& info = it->second;
      // Effective announce count: joined ranks are implicitly ready, but
      // only toward DEFAULT-process-set world tensors (wire names of other
      // sets carry a "\x1f" prefix the joined client cannot synthesize
      // for; join is a world-level operation in the reference too).
      bool world_level = info.required == 0 &&
                         it->first.find('\x1f') == std::string::npos;
      // The readiness threshold, materialized HERE (not at announce time):
      // raw required 0 means "the full world", which a clean LEAVE
      // (protocol v6) may have shrunk since the announce — the effective
      // world is what the survivors can actually deliver.
      int req = info.required ? info.required : eff_world();
      int have = static_cast<int>(info.ready_ranks.size());
      if (world_level) {
        for (int jr : joined)
          if (!info.ready_ranks.count(jr)) ++have;
        // A leaver that announced before departing would have aborted the
        // fleet (outstanding work); a leaver that had NOT announced simply
        // stops being counted — but it may have been counted implicitly
        // while joined, so clamp against the shrunk threshold.
        if (have > req) have = req;
      }
      // A collective that needs real data from a joined rank cannot be
      // satisfied with synthesized identity values: answer with a
      // per-tensor error instead of fabricating data (broadcast from a
      // joined root / allgather / alltoall — the reference errors here).
      if (!info.errored && world_level && !joined.empty() &&
          (info.data_dep == -2 ||
           (info.data_dep >= 0 && joined.count(info.data_dep)))) {
        std::string who;
        for (int jr : joined) {
          if (info.data_dep >= 0 && jr != info.data_dep) continue;
          if (!who.empty()) who += ",";
          who += std::to_string(jr);
        }
        errs.emplace_back(
            it->first, "tensor '" + it->first + "' requires data from " +
                           (info.data_dep >= 0 ? "root rank [" : "ranks [") +
                           who + "] which joined; collectives that need a "
                           "joined rank's data cannot run until all ranks "
                           "join");
        if (have >= req) {
          it = pending.erase(it);
          continue;
        }
        ++it;
        continue;
      }
      if (!info.grouped_ranks.empty() && !info.ungrouped_ranks.empty()) {
        // Grouped on some ranks, ungrouped on others: batching at the
        // fusion threshold would diverge → mismatched fused programs.
        std::string g, u;
        for (int rr : info.grouped_ranks) {
          if (!g.empty()) g += ",";
          g += std::to_string(rr);
        }
        for (int rr : info.ungrouped_ranks) {
          if (!u.empty()) u += ",";
          u += std::to_string(rr);
        }
        errs.emplace_back(
            it->first, "tensor '" + it->first +
                           "' negotiation failed: ranks [" + g +
                           "] submitted it as a GROUPED collective but "
                           "ranks [" + u + "] submitted it ungrouped");
        if (have >= req) {
          it = pending.erase(it);
          continue;
        }
        ++it;
        continue;
      }
      if (info.errored) {
        // Per-tensor error naming every rank on each side of the
        // divergence, rebuilt each round so late announcers are included.
        std::string msg = "tensor '" + it->first +
                          "' negotiation failed: mismatched submissions: ";
        bool first_d = true;
        for (auto& [d, ranks] : info.by_digest) {
          if (!first_d) msg += " vs ";
          first_d = false;
          std::string rs;
          for (int rr : ranks) {
            if (!rs.empty()) rs += ",";
            rs += std::to_string(rr);
          }
          msg += "ranks [" + rs + "] announced " + d;
        }
        errs.emplace_back(it->first, msg);
        if (have >= req) {
          it = pending.erase(it);
          continue;
        }
        ++it;
        continue;
      }
      if (have >= req) {
        // Slot-bit verdict only when every rank can resolve it: the slot
        // exists, every announcer was (or is being, via this round's
        // assigns broadcast) taught it, and no rank is joined (joined
        // ranks need the digest string to synthesize a contribution).
        if (joined.empty() && info.slot >= 0) {
          ready_slots.push_back(static_cast<uint32_t>(info.slot));
          ready_slot_first.push_back(info.round_created == round_no ? 1 : 0);
        } else
          ready.emplace_back(info.order, it->first, info.digest, info.group);
        it = pending.erase(it);
        continue;
      }
      double age =
          std::chrono::duration<double>(now - info.first_seen).count();
      if (age > stall_warn_s && !info.warned) {
        info.warned = true;
        std::string missing;
        for (int r = 0; r < world; ++r) {
          // Joined ranks are exempt only where they get implicit-ready
          // credit (world-level tensors); for subgroup tensors a joined
          // member really is the missing party — name it.  Clean leavers
          // are never "missing": they stopped counting entirely.
          if (left.count(r)) continue;
          if (!info.ready_ranks.count(r) &&
              !(world_level && joined.count(r))) {
            if (!missing.empty()) missing += ",";
            missing += std::to_string(r);
          }
        }
        warns.push_back("stall: tensor '" + it->first + "' waited " +
                        std::to_string(age) + "s; missing ranks [" + missing +
                        "]");
      }
      ++it;
    }
    std::sort(ready.begin(), ready.end());
    if (eff_world() > 0 && static_cast<int>(joined.size()) == eff_world()) {
      // Every rank joined: announce the epoch end (digest = last joiner)
      // and reset so the world can resume normal collectives.
      ready.emplace_back(UINT64_MAX, "\x1f__all_joined__",
                         std::to_string(last_joined), "-1");
      joined.clear();
      last_joined = -1;
    }

    // ---- speculative readiness (protocol v7).  Validate last round's
    // prediction against THIS round's actual slot verdicts: a predicted
    // slot that did not go ready is a mispredict — its streak resets, so
    // speculation disengages for it until the streak rebuilds through
    // normal rounds (the speculating client's early-consumed verdict is
    // absorbed by the merge of its next announce into the still-pending
    // entry; nothing to repair here).
    {
      std::set<uint32_t> ready_now(ready_slots.begin(), ready_slots.end());
      std::set<uint32_t> carried;
      if (!pred_slots.empty()) {
        for (uint32_t s : pred_slots) {
          if (ready_now.count(s)) {
            // Validated: after a long consecutive run of good
            // predictions the slot earns one step of its instability
            // penalty back (slow decay — see the field comment).
            if (s < cache_recs.size() && cache_recs[s].unstable > 0 &&
                ++cache_recs[s].valid_run >= kValidRunDecay) {
              --cache_recs[s].unstable;
              cache_recs[s].valid_run = 0;
            }
            continue;
          }
          // Not ready: distinguish a genuine mispredict (SOMEONE
          // announced the slot — a speculating client may have consumed
          // the verdict, and the partial announce proves a rank skipped)
          // from an idle round (NOBODY announced it — the engine's
          // timer-driven cycles legitimately interleave empty rounds
          // between step bursts; no client can have speculated, because
          // speculating requires announcing, so the prediction simply
          // CARRIES to the next round with its streak intact).
          bool announced = s < cache_recs.size() &&
                           pending.count(cache_recs[s].name) > 0;
          if (announced || s >= cache_recs.size() ||
              !cache_recs[s].live) {
            ++spec_mispredicts;
            if (s < cache_recs.size()) {
              // Per-slot backoff (ISSUE 12): beyond resetting the streak,
              // escalate this slot's re-qualification threshold so a
              // chronically unstable announce pattern withholds ONLY this
              // slot from future predictions — a repeated mispredict
              // would otherwise keep zeroing every speculating client's
              // engagement streak fleet-wide.
              cache_recs[s].streak = 0;
              cache_recs[s].valid_run = 0;
              if (cache_recs[s].unstable < 6) ++cache_recs[s].unstable;
            }
          } else {
            carried.insert(s);
          }
        }
        pred_slots.clear();
      }
      // Bound the carry: a prediction for a tensor the workload stopped
      // submitting must not ride every response forever.  Dropping it
      // keeps the streak, so the next use re-predicts immediately.
      if (!carried.empty()) {
        if (++pred_carry_rounds > 256) carried.clear();
      } else {
        pred_carry_rounds = 0;
      }
      // Streak update: ready-on-first-announce extends it, a slow
      // (multi-round) resolution resets it, and a slot left PENDING this
      // round resets it too — "k consecutive rounds" means exactly that.
      for (size_t i = 0; i < ready_slots.size(); ++i) {
        uint32_t s = ready_slots[i];
        if (s >= cache_recs.size()) continue;
        CacheRec& rec = cache_recs[s];
        rec.streak = ready_slot_first[i] ? rec.streak + 1 : 0;
      }
      for (auto& [n, info] : pending)
        if (info.slot >= 0 &&
            info.slot < static_cast<int64_t>(cache_recs.size()))
          cache_recs[info.slot].streak = 0;
      if (!left_this_round.empty()) {
        // A clean LEAVE shrinks the effective world mid-stream: every
        // streak restarts against the new readiness threshold.
        for (auto& rec : cache_recs) rec.streak = 0;
      }
      // Emit the next-round prediction: every rank v7, nobody joined, no
      // membership change this round, and only slots that went ready THIS
      // round with a mature streak (so the clients re-announcing them next
      // round is the overwhelmingly likely case).
      bool all_v7 = spec_ready_after > 0 && joined.empty() &&
                    left_this_round.empty() && !join_started;
      if (all_v7)
        for (int r = 0; r < world; ++r)
          if (!left.count(r) && !v7[r].load()) {
            all_v7 = false;
            break;
          }
      if (all_v7) {
        for (size_t i = 0; i < ready_slots.size(); ++i) {
          uint32_t s = ready_slots[i];
          if (s >= cache_recs.size() || !cache_recs[s].live) continue;
          // Per-slot qualification: an unstable slot must rebuild a
          // streak of spec_ready_after << unstable (capped) before it is
          // predicted again — the withholding that keeps one flaky
          // tensor from disengaging speculation for the stable ones.
          uint64_t need = static_cast<uint64_t>(spec_ready_after)
              << std::min<uint32_t>(cache_recs[s].unstable, 6u);
          if (static_cast<uint64_t>(cache_recs[s].streak) >= need)
            pred_slots.insert(s);
        }
        // Idle-round carry: unconsumed predictions stand (re-emitted so
        // clients, whose predictions are one-round-valid, stay primed).
        pred_slots.insert(carried.begin(), carried.end());
        spec_predictions += pred_slots.size();
      }
    }

    std::vector<uint8_t> resp;
    put_u32(&resp, static_cast<uint32_t>(ready.size()));
    for (auto& [ord, name, digest, group] : ready) {
      put_str(&resp, name);
      put_str(&resp, digest);
      put_str(&resp, group);
    }
    put_u32(&resp, static_cast<uint32_t>(warns.size()));
    for (auto& w : warns) put_str(&resp, w);
    put_u32(&resp, static_cast<uint32_t>(errs.size()));
    for (auto& [name, msg] : errs) {
      put_str(&resp, name);
      put_str(&resp, msg);
    }
    put_u32(&resp, static_cast<uint32_t>(assigns.size()));
    for (auto& [id, rec] : assigns) {
      put_str(&resp, rec.name);
      put_str(&resp, rec.digest);
      put_str(&resp, rec.datadep);
      put_u16(&resp, rec.required);
      put_u16(&resp, rec.grouped);
      put_u32(&resp, id);
    }
    // Ready bitvector (steady-state fast path) + coordinated evictions.
    uint32_t max_slot = 0;
    for (uint32_t s : ready_slots) max_slot = std::max(max_slot, s + 1);
    uint32_t bv_bytes = (max_slot + 7) / 8;
    put_u32(&resp, bv_bytes);
    size_t bv_off = resp.size();
    resp.resize(resp.size() + bv_bytes, 0);
    for (uint32_t s : ready_slots) resp[bv_off + s / 8] |= (1u << (s % 8));
    put_u32(&resp, static_cast<uint32_t>(evictions.size()));
    for (uint32_t s : evictions) put_u32(&resp, s);
    // Monitor section (protocol v3): this round's blobs, re-broadcast to
    // every rank.  Appended even when empty — the magic is the server's
    // capability advertisement clients version-gate on.  Bounded by
    // kMonSectionCap: at very large worlds a synchronized reporting
    // interval lands every rank's blob in one round, and the section must
    // stay far from the client receive cap — the overflow is dropped
    // (those ranks' tables lag one interval, nothing worse).
    size_t mon_budget = kMonSectionCap;
    std::vector<std::pair<int, std::string>*> mon_send;
    for (auto& b : mon_blobs) {
      if (b.second.size() + 8 > mon_budget) continue;
      mon_budget -= b.second.size() + 8;
      mon_send.push_back(&b);
    }
    put_u32(&resp, kMonMagic);
    put_u32(&resp, static_cast<uint32_t>(mon_send.size()));
    for (auto* b : mon_send) {
      put_u32(&resp, static_cast<uint32_t>(b->first));
      put_u32(&resp, static_cast<uint32_t>(b->second.size()));
      resp.insert(resp.end(), b->second.begin(), b->second.end());
    }
    // Clean-LEAVE notice (protocol v6): ranks that departed THIS round.
    // Appended only on rounds where someone actually left (warm rounds
    // carry zero extra bytes — frame-guarded) and, empty, on round 1 as
    // the capability ad; it rides AFTER the v4/v5 ads below so older
    // clients latch everything they understand before their trailing
    // walk stops at the unknown magic.
    // Fault-tolerance capability ad (protocol v4): round 1's response only,
    // so the warm path carries zero extra bytes — see the header comment.
    if (round_no == 1) {
      put_u32(&resp, kFltMagic);
      put_u32(&resp, 0);
      // Hierarchical-control-plane capability ad (protocol v5): also
      // round-1 only.  Appended AFTER FLT1 so pre-v5 clients — whose
      // trailing walk stops at the first unknown magic — still latch
      // their fault capability before ignoring the rest.
      put_u32(&resp, kAggMagic);
      put_u32(&resp, 0);
    }
    if (round_no == 1 || !left_this_round.empty()) {
      put_u32(&resp, kLeaveMagic);
      put_u32(&resp, 4 + 4 * static_cast<uint32_t>(left_this_round.size()));
      put_u32(&resp, static_cast<uint32_t>(left_this_round.size()));
      for (int r : left_this_round) put_u32(&resp, static_cast<uint32_t>(r));
    }
    // Zero-RTT prediction section (protocol v7): appended only on rounds
    // that actually predict — the warm path with speculation off carries
    // zero extra bytes — plus an empty section on round 1 as the
    // capability ad.  LAST among the trailing sections: pre-v7 clients
    // stop their order-agnostic-until-unknown walk here having latched
    // every older capability.
    if (round_no == 1 || !pred_slots.empty()) {
      put_u32(&resp, kZrtMagic);
      put_u32(&resp, 4 + 4 * static_cast<uint32_t>(pred_slots.size()));
      put_u32(&resp, static_cast<uint32_t>(pred_slots.size()));
      for (uint32_t s : pred_slots) put_u32(&resp, s);
    }
    // Attempt EVERY connection before honoring a failure: one dead/closing
    // peer must not cut the survivors off from a round's computed verdicts
    // (they may contain the ready broadcast that lets them finish cleanly).
    // A failed write marks the connection's ranks dead and the survivors
    // get a typed ABORT (queued behind the response they just received;
    // consumed at their next recv) instead of a blind socket sever.  One
    // write per connection: an agent fans the (already rank-agnostic)
    // response down to its local ranks itself.
    std::set<int> write_dead;
    for (Conn& c : conns) {
      if (c.left) continue;   // departed cleanly: no response owed
      if (!write_frame(c.fd, resp)) {
        c.sock_dead = true;
        poller.remove(c.fd);
        for (int r : c.ranks) write_dead.insert(r);
      }
    }
    if (!write_dead.empty()) {
      if (!stop.load()) {
        std::string who;
        for (int r : write_dead) {
          if (!who.empty()) who += ",";
          who += std::to_string(r);
        }
        broadcast_abort(write_dead,
                        "rank(s) [" + who +
                            "] lost connection while the round " +
                            std::to_string(round_no) +
                            " response was being broadcast");
      }
      stop.store(true);
    }
    // Freed slot ids become reusable only now that every client has (or
    // will, before its next request) processed the eviction broadcast —
    // a same-round reassignment could otherwise collide with in-flight
    // bit announces for the old tuple.
    for (uint32_t s : evictions) cache_free.push_back(s);
    stat_service_ns.fetch_add(static_cast<uint64_t>(
        std::chrono::duration_cast<std::chrono::nanoseconds>(
            Clock::now() - svc_t0)
            .count()));
    stat_rounds.fetch_add(1);
  }
  // fds are closed by hvdtpu_server_stop after the thread joins.
}

struct Client {
  int fd = -1;
};

}  // namespace

extern "C" {

void* hvdtpu_server_start(int port, int world, double stall_warn_s,
                          int cache_capacity, int round_deadline_ms,
                          int spec_ready_after, int spec_seed) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  ::setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0 ||
      ::listen(fd, world) < 0) {
    ::close(fd);
    return nullptr;
  }
  auto* s = new Server();
  s->listen_fd = fd;
  s->world = world;
  s->stall_warn_s = stall_warn_s;
  s->cache_capacity = cache_capacity < 0 ? 0
      : static_cast<size_t>(cache_capacity);
  s->round_deadline_ms = round_deadline_ms < 0 ? 0 : round_deadline_ms;
  s->spec_ready_after = spec_ready_after < 0 ? 0 : spec_ready_after;
  // The seed is only meaningful below the qualification threshold (a
  // fresh slot must still prove ONE ready-on-first-announce round), and
  // only while speculation is armed at all.
  s->spec_seed = (spec_seed < 0 || s->spec_ready_after == 0)
      ? 0 : std::min(spec_seed, s->spec_ready_after);
  s->fds = std::make_unique<std::atomic<int>[]>(world);
  s->v4 = std::make_unique<std::atomic<char>[]>(world);
  s->v5 = std::make_unique<std::atomic<char>[]>(world);
  s->v6 = std::make_unique<std::atomic<char>[]>(world);
  s->v7 = std::make_unique<std::atomic<char>[]>(world);
  for (int i = 0; i < world; ++i) {
    s->fds[i].store(-1);
    s->v4[i].store(0);
    s->v5[i].store(0);
    s->v6[i].store(0);
    s->v7[i].store(0);
  }
  s->loop = std::thread([s] { s->run(); });
  return s;
}

// Root-side service accounting: out[0] = rounds served, out[1] = mean
// root service microseconds per round (gather-complete -> last response
// write).  Safe while the server runs (atomics) — the negotiation-scaling
// bench reads it before stopping the server.
int hvdtpu_server_stats(void* handle, double* out) {
  auto* s = static_cast<Server*>(handle);
  if (!s || !out) return -1;
  uint64_t rounds = s->stat_rounds.load();
  uint64_t ns = s->stat_service_ns.load();
  out[0] = static_cast<double>(rounds);
  out[1] = rounds ? static_cast<double>(ns) / 1e3 / rounds : 0.0;
  return 0;
}

void hvdtpu_server_stop(void* handle) {
  auto* s = static_cast<Server*>(handle);
  if (!s) return;
  // shutdown (not close) unblocks the loop thread's blocking accept/recv;
  // actual closes happen only after the join so no fd is closed (and
  // potentially recycled) while the loop might still read it.
  s->stop.store(true);
  ::shutdown(s->listen_fd, SHUT_RDWR);
  int hs = s->handshake_fd.exchange(-2);
  if (hs >= 0) ::shutdown(hs, SHUT_RDWR);
  // Let an in-flight round finish broadcasting its responses before
  // severing the sockets (phase_mu comment): without this, peers whose
  // response for the CURRENT round had not been written yet fail their
  // round with a pending entry.  Timed: a peer wedged enough to block a
  // small write for 5s is a dead peer; proceed and sever.
  bool locked = s->phase_mu.try_lock_for(std::chrono::seconds(5));
  for (int i = 0; i < s->world; ++i) {
    int fd = s->fds[i].load();
    if (fd >= 0) ::shutdown(fd, SHUT_RDWR);
  }
  if (locked) s->phase_mu.unlock();
  if (s->loop.joinable()) s->loop.join();
  // If we took ownership of a mid-handshake fd (exchanged to -2 above),
  // run() deliberately did not close it — close it now, after the join.
  if (hs >= 0) ::close(hs);
  ::close(s->listen_fd);
  // An agent connection's fd appears once per claimed rank: close each
  // DISTINCT fd exactly once (a double close could hit a recycled number).
  std::set<int> closed;
  for (int i = 0; i < s->world; ++i) {
    int fd = s->fds[i].load();
    if (fd >= 0 && closed.insert(fd).second) ::close(fd);
  }
  delete s;
}

void* hvdtpu_client_connect(const char* host, int port, int rank,
                            int timeout_ms) {
  auto deadline = Clock::now() + std::chrono::milliseconds(timeout_ms);
  std::string port_str = std::to_string(port);
  while (Clock::now() < deadline) {
    // Resolve every attempt (DNS, not just dotted IPv4 — hostnames from
    // `-H node1:2,...` must work; resolution can also succeed late while
    // hosts boot).
    addrinfo hints{};
    hints.ai_family = AF_UNSPEC;
    hints.ai_socktype = SOCK_STREAM;
    addrinfo* res = nullptr;
    if (::getaddrinfo(host, port_str.c_str(), &hints, &res) != 0) {
      std::this_thread::sleep_for(std::chrono::milliseconds(200));
      continue;
    }
    for (addrinfo* ai = res; ai; ai = ai->ai_next) {
      int fd = ::socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
      if (fd < 0) continue;
      if (::connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) {
        int one = 1;
        ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
        uint32_t r = static_cast<uint32_t>(rank);
        if (!write_exact(fd, &r, 4)) {
          ::close(fd);
          break;  // retry from scratch
        }
        ::freeaddrinfo(res);
        auto* c = new Client();
        c->fd = fd;
        return c;
      }
      ::close(fd);
    }
    ::freeaddrinfo(res);
    std::this_thread::sleep_for(std::chrono::milliseconds(50));
  }
  return nullptr;
}

// Send half of a lock-step round: write the request frame.  0 on success,
// -1 on a dead/closed socket.
int hvdtpu_client_send(void* handle, const uint8_t* req, int req_len) {
  auto* c = static_cast<Client*>(handle);
  if (!c || c->fd < 0) return -1;
  std::vector<uint8_t> payload(req, req + req_len);
  return write_frame(c->fd, payload) ? 0 : -1;
}

// Receive half: block for the response frame, bounded by timeout_ms
// (<= 0 = wait forever, the pre-v4 behavior).  Returns the response
// length, -1 on a dead socket, -2 on overflow, -3 on deadline expiry.
// The deadline bounds the ENTIRE frame, not just its first byte: a
// coordinator wedged mid-frame-write (SIGSTOPped / paged out after the
// length prefix) must still surface as RoundTimeoutError — this timeout
// is the documented backstop for exactly that wedged-coordinator case,
// where the server-side round deadline cannot help.
int hvdtpu_client_recv(void* handle, uint8_t* resp_buf, int resp_cap,
                       int timeout_ms) {
  auto* c = static_cast<Client*>(handle);
  if (!c || c->fd < 0) return -1;
  std::vector<uint8_t> resp;
  if (timeout_ms > 0) {
    auto deadline = Clock::now() + std::chrono::milliseconds(timeout_ms);
    int rc = read_frame_deadline(c->fd, &resp, deadline);
    if (rc == 0) return -3;
    if (rc < 0) return -1;
  } else if (!read_frame(c->fd, &resp)) {
    return -1;
  }
  if (static_cast<int>(resp.size()) > resp_cap) return -2;
  if (!resp.empty()) std::memcpy(resp_buf, resp.data(), resp.size());
  return static_cast<int>(resp.size());
}

// 1 when a frame is already readable (used to drain a queued ABORT before
// sending the next request — a send into a reset socket would make the
// kernel discard the buffered abort frame), else 0.
int hvdtpu_client_pending(void* handle) {
  auto* c = static_cast<Client*>(handle);
  if (!c || c->fd < 0) return 0;
  pollfd pfd{c->fd, POLLIN, 0};
  return ::poll(&pfd, 1, 0) > 0 ? 1 : 0;
}

// One lock-step round: send req frame, block for response frame.
// Returns response length, 0 on empty response, -1 on error, -2 if the
// response exceeds resp_cap.  (Legacy composite of send + recv, kept for
// unit tests and out-of-tree callers.)
int hvdtpu_client_round(void* handle, const uint8_t* req, int req_len,
                        uint8_t* resp_buf, int resp_cap) {
  int rc = hvdtpu_client_send(handle, req, req_len);
  if (rc < 0) return rc;
  return hvdtpu_client_recv(handle, resp_buf, resp_cap, 0);
}

// Unblock a thread stuck in hvdtpu_client_round (recv returns 0 after the
// socket shutdown) WITHOUT freeing the Client — call before client_close so
// shutdown ordering can't use-after-free a blocked round.
void hvdtpu_client_interrupt(void* handle) {
  auto* c = static_cast<Client*>(handle);
  if (c && c->fd >= 0) ::shutdown(c->fd, SHUT_RDWR);
}

void hvdtpu_client_close(void* handle) {
  auto* c = static_cast<Client*>(handle);
  if (!c) return;
  if (c->fd >= 0) ::close(c->fd);
  delete c;
}

}  // extern "C"
